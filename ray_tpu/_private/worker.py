"""CoreWorker: per-process runtime linked into drivers and workers.

Analog of the reference's core_worker library
(ray: src/ray/core_worker/core_worker.h:295 + python/ray/_raylet.pyx:3309).
One instance per process, in one of two modes:
  - "driver": created by ray_tpu.init(); submits tasks, owns returned objects
  - "worker": created by worker_main in agent-forked processes; executes
    tasks/actors and doubles as a submitter for nested tasks

Subsystems, each mirroring a reference component:
  - FunctionManager: content-hash export of pickled functions/classes to the
    controller KV; lazy fetch+cache on workers
    (ray: python/ray/_private/function_manager.py:195,264)
  - LeaseManager: per-scheduling-key worker leases with reuse, pipelining and
    spillback redirects (ray: NormalTaskSubmitter normal_task_submitter.h:75)
  - actor submission: direct worker->worker calls with per-handle sequence
    numbers, address re-resolution on restart
    (ray: ActorTaskSubmitter transport/actor_task_submitter.cc)
  - execution: ordered per-caller actor queues, threaded / asyncio actors
    (ray: transport/actor_scheduling_queue.cc, fiber.h)
  - ownership: owned-object table with inline values, locations, borrower
    counts, and lineage resubmission (ray: reference_count.cc,
    task_manager.cc, object_recovery_manager.h:41)

The asyncio loop always runs on a dedicated IO thread; public API calls
bridge onto it with run_coroutine_threadsafe (the GIL-discipline analog of
_raylet.pyx keeping the hot path out of user threads).
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import contextlib
import hashlib
import itertools
import logging
import os
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any


from ray_tpu._private import failpoints
from ray_tpu._private import memledger
from ray_tpu._private import spans
from ray_tpu._private.config import Config
from ray_tpu._private.ids import ActorID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_store import MemoryStore
from ray_tpu._private.rpc import (ClientPool, ConnectionLost, RemoteError,
                                  RpcServer, Subscriber)
from ray_tpu._private.serialization import (SerializedValue,
                                            deserialize_with_refs,
                                            dumps_function, loads_function,
                                            serialize)
from ray_tpu.exceptions import (ActorDiedError, ActorError, GetTimeoutError,
                                ObjectLostError, TaskCancelledError, TaskError,
                                WorkerCrashedError)
from ray_tpu.object_ref import ObjectRef, set_release_hook

from ray_tpu._private.actor_state import (REPLY_EVICTED,
                                          ActorInstance,
                                          ActorSubmitState,
                                          StreamState)
from ray_tpu._private.lease_manager import LeaseManager, PendingTask

logger = logging.getLogger(__name__)

_global_worker: "CoreWorker | None" = None


def global_worker() -> "CoreWorker":
    if _global_worker is None:
        raise RuntimeError("ray_tpu is not initialized; call ray_tpu.init()")
    return _global_worker


def set_global_worker(w: "CoreWorker | None") -> None:
    global _global_worker
    _global_worker = w


def _freeze(v):
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        # Label constraints nest value lists; a raw list would make the
        # scheduling key unhashable.
        return tuple(_freeze(x) for x in v)
    return v


# --------------------------------------------------------------------------
@dataclass
class OwnedObject:
    """Owner-side record for one object (ray: reference_count.cc entry)."""

    state: str = "pending"           # pending | inline | stored | error
    frames: list[bytes] | None = None
    locations: list[str] = field(default_factory=list)
    # Serialized payload size, learned at fulfillment (ray: object size in
    # the owner's reference table; feeds Data's resource manager).
    size: int = 0
    error: BaseException | None = None
    local_refs: int = 0
    borrowers: int = 0
    # Refs nested inside this object's value: (object_id, owner_addr) pins
    # added when the value was created (put / task return), released when
    # this object is freed (ray: reference_count.cc contained-object refs).
    contained: list = field(default_factory=list)
    # Lineage for reconstruction (ray: TaskManager::ResubmitTask).
    submit_spec: tuple | None = None
    retries_left: int = 0


class _UntrackedRef(ObjectRef):
    """Internal temporary ref: participates in no reference counting.
    Bare ObjectRef construction inside the runtime must use this class —
    a plain ObjectRef's __del__ would decrement counts (owner local_refs /
    borrow table) that were never incremented for it."""

    __slots__ = ()

    def __del__(self):
        pass


class _SyncCall:
    """In-flight fused sync actor call (ISSUE-1 fast path): the return-0
    object id maps to this record so a get() right after the submit can
    block on the reply future directly — resolved on the rpc IO thread,
    no event-loop handoff on the caller's critical path."""

    __slots__ = ("task", "cfut", "client")

    def __init__(self, task, cfut, client):
        self.task = task
        self.cfut = cfut
        self.client = client


_EMPTY_ARGS_FRAMES: list | None = None


def _empty_args_frames() -> list:
    """Cached pickle of ((), {}) — the payload of every no-arg call.
    Frames are immutable bytes; a shallow list copy keeps per-task blob
    lists independent."""
    global _EMPTY_ARGS_FRAMES
    if _EMPTY_ARGS_FRAMES is None:
        sv = serialize(((), {}))
        _EMPTY_ARGS_FRAMES = [
            f.tobytes() if isinstance(f, memoryview) else f
            for f in sv.frames]
    return list(_EMPTY_ARGS_FRAMES)


def _copy_error(e: BaseException) -> BaseException:
    """Shallow-copy a cached error before raising it: raising the cached
    instance would attach the caller's traceback to it, pinning every frame
    (and every actor handle / large object in those frames) for as long as
    the error stays cached in the memory store."""
    import copy

    try:
        err = copy.copy(e)
        err.__traceback__ = None
        return err
    except Exception:  # noqa: BLE001 - uncopyable exception
        return e


class CoreWorker:
    def __init__(self, mode: str, controller_addr: str, agent_addr: str,
                 config: Config, worker_id: str | None = None,
                 node_id: str = "", job_id: str = "", pub_addr: str = "",
                 namespace: str = "default"):
        self.mode = mode
        self.config = config
        self.controller_addr = controller_addr
        self.agent_addr = agent_addr
        self.pub_addr = pub_addr
        self.worker_id = worker_id or WorkerID.from_random().hex()
        self.node_id = node_id
        self.job_id = job_id
        self.namespace = namespace
        # Flight-recorder process label: harvest output names spans by
        # role, not bare pid (driver vs executor worker).
        spans.set_process_label(
            "driver" if mode == "driver"
            else f"worker:{self.worker_id[:12]}")
        self.memory = MemoryStore()
        self.owned: dict[bytes, OwnedObject] = {}
        # Borrower-side table: refs this process holds but does not own
        # (object_id -> {count, owner}); see _register_borrows.
        self.borrows: dict[bytes, dict] = {}
        # Guards every owned/borrows counter mutation: ObjectRef.__del__
        # runs on arbitrary GC threads, user code on executor threads, RPC
        # handlers on the loop — bare `x -= 1` is a lost-update race.
        # RLock because _free_object (under lock) releases contained pins,
        # which re-enter the lock (ray: absl::Mutex on reference_count).
        self._ref_lock = threading.RLock()
        # Creation-arg pins per actor created by this process
        # (actor_id -> [(object_id, owner_addr)]).
        self.actor_creation_borrows: dict[str, list] = {}
        # Burst-fused actor registrations (RAY_TPU_ACTOR_WAVES): unnamed
        # creations enqueue here and a loop-side flusher coalesces the
        # burst into ONE create_actors controller round trip (the
        # call_and_wait fusion shape applied to registration).  The
        # reply for an unnamed actor is fully determined client-side, so
        # the user thread never waits on it.
        self._actor_reg_batch: list[tuple[dict, list]] = []
        self._actor_reg_lock = threading.Lock()
        self._actor_reg_task: asyncio.Task | None = None
        self.functions: dict[str, Any] = {}
        self._exported: set[str] = set()
        # id(fn) -> (fid, weakref) — see export_function.
        self._fid_by_identity: dict[int, tuple] = {}
        self.actors_hosted: dict[str, ActorInstance] = {}
        self.actor_states: dict[str, ActorSubmitState] = {}
        self.current_actor_id: str | None = None
        self.current_task_id: str | None = None
        # PG bundle of the currently-executing task (tasks only; actor
        # methods resolve through their ActorInstance.bundle_key).
        self.current_bundle_key: str | None = None
        # Lease resources + runtime env of the executing task, for
        # runtime_context.get_assigned_resources/get_runtime_env_string.
        self.current_resources: dict | None = None
        self.current_runtime_env: dict | None = None
        # Trace context of the currently-executing task (ray: OpenTelemetry
        # propagation, util/tracing/tracing_helper.py): child submissions
        # inherit trace_id, and task events / profiling spans carry it.
        self.current_trace: dict | None = None
        # Driver address of the job whose task is currently executing
        # (propagated in task headers like `trace`); None outside tasks.
        self.current_driver_addr: str | None = None
        self._put_seq = itertools.count()
        self._cancelled: set[bytes] = set()
        # task_id -> StreamState for streaming-generator tasks this process
        # submitted (owner side; mutated only on the IO loop).
        self.streams: dict[bytes, StreamState] = {}
        # Abandoned streams (generator GC'd): late items must NOT re-create
        # state (it would never be removed and would pin the item refs
        # forever).  Bounded FIFO of task_ids.
        self._dead_streams: set[bytes] = set()
        self._dead_stream_order: list[bytes] = []
        # return-0 object id -> task_id, recorded at streaming submits so
        # the generator wrapper can find its stream (popped immediately).
        self._ret0_task_ids: dict[bytes, bytes] = {}
        self._oom_worker_addrs: set[str] = set()
        # Known-dead worker addresses (set for O(1) membership on the
        # push hot path + FIFO order for bounded eviction).  Entries are
        # REVIVED when a fresh worker provably lives at the address (lease
        # grant / actor-alive event) — ephemeral ports get reused.
        self._dead_worker_addrs: set[str] = set()
        self._dead_addr_order: list[str] = []
        # Worker-local cache of this worker's own task returns: a consumer
        # task scheduled here reads them without asking the owner (ray:
        # locality — plasma already holds the return on the producing
        # node).  Bounded FIFO; consumers also evict after use.
        self._return_cache: list[bytes] = []
        self._running_async: dict[bytes, asyncio.Task] = {}
        self._shutdown = threading.Event()
        self._task_events: list[dict] = []
        self._event_tag: tuple[str, str] | None = None
        # Direct mapping of the local node store (plasma-client analog,
        # ray: plasma/client.cc mmaps store memory into the worker): puts
        # and gets of node-store objects bypass the agent RPC entirely.
        self.store_name: str = os.environ.get("RAY_TPU_STORE_NAME", "")
        self._arena = None
        self._arena_tried = False
        self._arena_lock = threading.Lock()
        # Same-host peer arenas for the direct-shm pull fast path:
        # agent addr -> shm name (None = not native / not same host),
        # shm name -> mapped Arena.  See _pull_direct_shm.
        self._peer_shm: dict[str, str | None] = {}
        self._peer_arenas: dict[str, Any] = {}
        # Put-path attribution (profiling.put_stats): arena-direct puts
        # vs silent degradations to the agent store_put RPC, with the
        # first fallback cause kept (and logged once) so "put is slow"
        # is diagnosable as "put is not using the arena".
        self._arena_puts = 0
        self._arena_fallbacks = 0
        self._arena_fallback_cause: str | None = None
        self.loop: asyncio.AbstractEventLoop = None  # set in start()
        self._default_executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="task-exec")
        # Batched cross-thread posts: call_soon_threadsafe costs a self-pipe
        # write (syscall) per call, which at thousands of submits/releases
        # per second dominates the submit path.  One wakeup drains many.
        self._post_pending: list = []
        self._post_scheduled = False
        # Outstanding call_nowait RPC tasks: flushed at shutdown so a
        # fire-and-forget notification posted right before exit (e.g.
        # remove_placement_group) still reaches the wire.
        self._nowait_tasks: set = set()
        self._post_mutex = threading.Lock()
        # return-0 object id -> _SyncCall for fused sync actor calls
        # (see _submit_actor_direct): entries are claimed by the first
        # get() on the ref and always cleaned up by the loop-side
        # finalize when the reply (or transport failure) lands.
        self._sync_calls: dict[bytes, _SyncCall] = {}
        # Fused-path counter (tests/bench assert the path engages) and
        # kill switch (A/B debugging: RAY_TPU_SYNC_FASTPATH=0).
        self._direct_sync_calls = 0
        self._sync_fastpath = os.environ.get(
            "RAY_TPU_SYNC_FASTPATH", "1") != "0"

    # ---------------------------------------------------------------- setup
    def start(self) -> None:
        started = threading.Event()
        self._io_thread = threading.Thread(
            target=self._io_main, args=(started,), name="raytpu-io",
            daemon=True)
        self._io_thread.start()
        started.wait(30.0)
        if self.loop is None:
            raise RuntimeError("IO loop failed to start")
        set_release_hook(self._release_local_ref)
        from ray_tpu._private.config import tune_gc

        tune_gc(framework_process=(self.mode != "driver"))
        if self.store_name and os.environ.get(
                "RAY_TPU_ARENA_WARM", "1") not in ("0", "false"):
            # Map + write-prefault the arena off the hot path: the lazy
            # first-use open costs ~250ms for a 512MB arena
            # (MADV_POPULATE_WRITE), which would land inside the first
            # big put otherwise.  Kill switch RAY_TPU_ARENA_WARM=0: a
            # boot storm of short-lived actors pays PTE population ×
            # every worker for puts that never come.
            threading.Thread(target=self.warm_arena, daemon=True,
                             name="raytpu-arena-warm").start()

    @property
    def driver_addr(self) -> str:
        """The owning job's driver address: this process for drivers,
        the submitting job's driver inside task/actor execution (falls
        back to this process for detached contexts)."""
        if self.mode == "driver":
            return self.address
        return self.current_driver_addr or self.address

    def _io_main(self, started: threading.Event) -> None:
        asyncio.run(self._io_async_main(started))

    async def _io_async_main(self, started: threading.Event) -> None:
        self.loop = asyncio.get_running_loop()
        from ray_tpu._private.stack_dump import register_loop
        register_loop(self.loop)
        # Transport sockets live on the process-wide rpc IO thread; this
        # component only closes ITS server/clients/subscriber on the way
        # out (the shared context is never terminated — in-process
        # cluster nodes coexist on it).
        self.server = RpcServer()
        self.clients = ClientPool()
        self.server.register_all(self)
        self.server.start()
        self.address = self.server.address
        self.lease_manager = LeaseManager(self)
        if self.pub_addr:
            self._subscribe_events(self.pub_addr)
        if self.mode == "worker":
            await self.clients.get(self.agent_addr).call(
                "register_worker",
                {"worker_id": self.worker_id, "addr": self.address},
                timeout=30.0)
        flusher = self.loop.create_task(self._event_flush_loop())
        started.set()
        try:
            # Asyncio-native shutdown signal.  Parking a default-executor
            # thread on self._shutdown.wait would deadlock interpreter
            # exit: concurrent.futures' _python_exit joins executor threads
            # BEFORE regular atexit callbacks run, so a driver that never
            # calls ray_tpu.shutdown() explicitly would hang forever.
            self._shutdown_async = asyncio.Event()
            if self._shutdown.is_set():
                self._shutdown_async.set()
            await self._shutdown_async.wait()
        finally:
            flusher.cancel()
            sub = getattr(self, "subscriber", None)
            if sub is not None:
                sub.close()
            self.server.close()
            self.clients.close()

    def _subscribe_events(self, pub_addr: str) -> None:
        """Subscribe to controller events (must run on the IO loop)."""
        self.pub_addr = pub_addr
        self.subscriber = Subscriber(address=pub_addr)
        self.subscriber.subscribe("actor", self._on_actor_event)
        self.subscriber.subscribe("worker", self._on_worker_event)
        self.subscriber.subscribe("node", self._on_node_event)
        if self.mode == "driver" and getattr(self, "log_to_driver", False):
            self.subscriber.subscribe("logs", self._on_log_lines)

    async def _on_worker_event(self, _topic: str, payload: dict) -> None:
        """Cluster-wide worker-death broadcast: mark the address dead and
        drop its client NOW — every pending call to it (e.g. a borrower's
        resolve_object against a dead owner) fails instead of waiting on
        a zmq DEALER that reconnects forever."""
        if payload.get("event") != "dead":
            return
        addr = payload.get("addr")
        if not addr or addr == self.address:
            return
        self._mark_addr_dead(addr)
        self.clients.drop(addr)

    async def _on_node_event(self, _topic: str, payload: dict) -> None:
        """Node death fan-out (round-9 MTTR fix): an object pull from a
        dead node's agent used to wait out the full transfer RPC timeout
        (120s per location) before recovery could start — the dominant
        term in crash-mid-chunked-pull MTTR.  Mark the dead agent's
        address and fail its in-flight calls NOW; a rejoining node
        (same address) is revived on its "alive" event."""
        addr = payload.get("agent_addr")
        if not addr or addr == self.agent_addr:
            return          # our own agent's fate is ours anyway
        if payload.get("event") == "dead":
            self._mark_addr_dead(addr)
            self.clients.drop(addr)
        elif payload.get("event") == "alive":
            self._revive_addr(addr)

    def _mark_addr_dead(self, addr: str) -> None:
        """The ONE bookkeeping site for the dead-address registry (the
        eviction ring must never hold duplicate entries, or popping an
        old duplicate would un-mark a currently-dead address)."""
        if addr in self._dead_worker_addrs:
            return
        self._dead_worker_addrs.add(addr)
        self._dead_addr_order.append(addr)
        while len(self._dead_addr_order) > 1024:
            self._dead_worker_addrs.discard(self._dead_addr_order.pop(0))

    async def _on_log_lines(self, _topic: str, payload: dict) -> None:
        """Print streamed worker logs on the driver console
        (ray: log_monitor-fed driver output, prefixed per worker)."""
        import sys

        node = payload.get("node_id", "?")
        for src, line in payload.get("lines", []):
            print(f"({src}, node={node}) {line}", file=sys.stderr)

    def connect_events(self, pub_addr: str) -> None:
        self.loop.call_soon_threadsafe(self._subscribe_events, pub_addr)

    def shutdown(self) -> None:
        set_release_hook(None)
        # Flush fire-and-forget notifications first: a remove_pg posted
        # just before exit must reach the wire or its reservation leaks
        # cluster-wide (nobody else reaps this driver's PGs).  Batched
        # actor registrations too — a detached actor created right
        # before exit must reach the controller.
        try:
            self.run(self._actor_regs_settled(), timeout=3.0)
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        try:
            self.run(self._drain_nowait(), timeout=3.0)
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        self._shutdown.set()
        ev = getattr(self, "_shutdown_async", None)
        if ev is not None and self.loop is not None:
            try:
                self.loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass
        self._io_thread.join(5.0)
        set_global_worker(None)

    def _post_to_loop(self, fn) -> None:
        """Run fn() on the IO loop; safe from any thread.  Posts made while
        a wakeup is already pending ride the same drain (one self-pipe
        write per burst instead of one per call)."""
        with self._post_mutex:
            self._post_pending.append(fn)
            if self._post_scheduled:
                return
            self._post_scheduled = True
        loop = self.loop
        try:
            if loop is None:
                raise RuntimeError("IO loop not running")
            loop.call_soon_threadsafe(self._drain_posts)
        except RuntimeError:
            # Reset so a later post retries the wakeup — a stuck True flag
            # would silently drop every future post (submit hangs).
            with self._post_mutex:
                self._post_scheduled = False
            raise

    def _drain_posts(self) -> None:
        while True:
            with self._post_mutex:
                pending = self._post_pending
                if not pending:
                    self._post_scheduled = False
                    return
                self._post_pending = []
            for fn in pending:
                try:
                    fn()
                except Exception:  # noqa: BLE001
                    logger.exception("posted callback failed")

    def run(self, coro, timeout: float | None = None):
        """Bridge a coroutine from any user thread onto the IO loop."""
        if threading.current_thread() is getattr(self, "_io_thread", None):
            # Blocking the loop on itself would deadlock forever (e.g. a
            # custom __setstate__ calling ray_tpu.get() during inline
            # deserialization) — fail loudly instead.
            coro.close()
            raise RuntimeError(
                "ray_tpu blocking API called from the runtime IO thread "
                "(e.g. inside a deserialization hook); move the call into "
                "task/actor code")
        # Hand-rolled bridge instead of run_coroutine_threadsafe: that
        # helper chains the Task to the concurrent Future with closures
        # that keep BOTH alive in a reference cycle, and each retains the
        # coroutine's exception.  Re-raising here then grows that
        # exception's traceback with the caller's frames, closing a cycle
        # (exc.tb → caller frame → future → Task → exc) that only a
        # CYCLIC gc pass reclaims — minutes away under tune_gc()'s raised
        # thresholds.  Everything the caller's frames reference (actor
        # handles, stream generators, arrays) is pinned that whole
        # window; a delayed ActorHandle.__del__ kill once starved a test
        # cluster of CPU leases and wedged the suite.  Here the exception
        # travels as a RESULT tuple: the Task keeps no payload and dies
        # by refcount the moment its done-callback returns, so liveness
        # never waits on the collector.
        cfut: concurrent.futures.Future = concurrent.futures.Future()
        loop = self.loop

        def _start():
            task = loop.create_task(coro)

            def _done(t):
                try:
                    payload = (True, t.result())
                except BaseException as e:  # noqa: BLE001
                    # Strip THIS frame from the traceback: with it, the
                    # exception would reference a frame whose locals
                    # reference the exception back — a refcount-immune
                    # cycle pinning the payload until a gc pass.
                    tb = e.__traceback__
                    if tb is not None:
                        e.__traceback__ = tb.tb_next
                    del tb   # else: frame-local ↔ frame self-cycle
                    payload = (False, e)
                try:
                    cfut.set_result(payload)
                except concurrent.futures.InvalidStateError:
                    pass

            task.add_done_callback(_done)

        loop.call_soon_threadsafe(_start)
        try:
            ok, val = cfut.result(timeout)
        finally:
            if cfut.done():
                cfut._result = None
            else:
                # Timed out: let the eventual payload free itself.
                cfut.add_done_callback(
                    lambda f: setattr(f, "_result", None))
        if ok:
            return val
        try:
            raise val
        finally:
            # raise grew val.__traceback__ to include THIS frame; the
            # frame-local `val` would close the cycle — drop it.
            del val

    async def acall(self, addr: str, method: str, header: dict | None = None,
                    blobs: list | None = None,
                    timeout: float | None = None) -> tuple[dict, list]:
        return await self.clients.get(addr).call(
            method, header or {}, blobs, timeout)

    def call(self, addr: str, method: str, header: dict | None = None,
             blobs: list | None = None,
             timeout: float | None = None) -> tuple[dict, list]:
        """Thread-safe RPC from user threads; client sockets are created on
        the IO loop (zmq asyncio sockets are loop-bound)."""
        return self.run(self.acall(addr, method, header, blobs, timeout))

    def call_nowait(self, addr: str, method: str,
                    header: dict | None = None, blobs: list | None = None,
                    timeout: float = 30.0) -> None:
        """Fire an RPC without blocking on its reply (errors are logged,
        not raised).  For notifications whose effect the caller never
        reads back directly — e.g. remove_placement_group, where the
        reference's GCS also tears down asynchronously.  Per-connection
        zmq ordering still serializes it before the caller's NEXT call to
        the same peer."""
        def _go():
            async def _run():
                try:
                    await self.clients.get(addr).call(
                        method, header, blobs, timeout=timeout)
                except Exception:  # noqa: BLE001 - fire-and-forget
                    logger.warning("call_nowait %s to %s failed", method,
                                   addr)
            t = self.loop.create_task(_run())
            self._nowait_tasks.add(t)
            t.add_done_callback(self._nowait_tasks.discard)

        self._post_to_loop(_go)

    async def _drain_nowait(self) -> None:
        pending = [t for t in self._nowait_tasks if not t.done()]
        if pending:
            await asyncio.wait(pending, timeout=2.0)

    # ------------------------------------------------------------ functions
    def export_function(self, fn: Any) -> str:
        # Identity cache: the same function object is submitted thousands of
        # times on the hot path; re-pickling + re-hashing it per call costs
        # ~100µs each (ray keeps the same discipline — the function is
        # exported once per (fn, job), function_manager.py:195).  Weakrefs,
        # not hard pins: a driver minting fresh closures per call must not
        # accumulate them (dead entries drop via the weakref callback).
        import weakref

        key = id(fn)
        hit = self._fid_by_identity.get(key)
        if hit is not None and hit[1]() is fn:
            return hit[0]
        blob = dumps_function(fn)
        fid = hashlib.blake2b(blob, digest_size=16).hexdigest()
        if fid not in self._exported:
            self.call(self.controller_addr, "kv_put",
                      {"ns": "fn", "key": fid}, [blob])
            self._exported.add(fid)
            self.functions[fid] = fn
        try:
            ref = weakref.ref(
                fn, lambda _r, k=key: self._fid_by_identity.pop(k, None))
            self._fid_by_identity[key] = (fid, ref)
        except TypeError:
            pass   # not weakref-able: skip caching
        return fid

    async def _fetch_function(self, fid: str) -> Any:
        fn = self.functions.get(fid)
        if fn is not None:
            return fn
        reply, blobs = await self.clients.get(self.controller_addr).call(
            "kv_get", {"ns": "fn", "key": fid})
        if not reply.get("found"):
            raise RuntimeError(f"function {fid} not found in KV")
        fn = await self.loop.run_in_executor(None, loads_function, blobs[0])
        self.functions[fid] = fn
        return fn

    # ----------------------------------------------------------- submission
    def submit_task(self, fn: Any, args: tuple, kwargs: dict,
                    options: dict) -> list[ObjectRef]:
        fid = self.export_function(fn)
        task_id = TaskID.from_random()
        num_returns = options.get("num_returns", 1)
        return_ids = [ObjectID.for_return(task_id, i).binary()
                      for i in range(num_returns)]
        resources = dict(options.get("resources") or {})
        resources.setdefault("CPU", options.get("num_cpus", 1))
        if options.get("num_tpus"):
            resources["TPU"] = options["num_tpus"]
        bundle_key = options.get("bundle_key")
        header, blobs, borrowed = self._build_task_payload(
            task_id.binary(), fid, args, kwargs, num_returns, resources,
            bundle_key, options)
        retries = options.get("max_retries",
                              self.config.default_task_max_retries)
        # venv tasks must not share leases with plain tasks — the worker
        # pool is keyed by env (runtime_env.venv_key on the agent side).
        venv_desc = (header.get("runtime_env") or {}).get("venv")
        scheduling_key = (fid, _freeze(resources), bundle_key,
                          options.get("affinity_node_id"),
                          options.get("affinity_soft", False),
                          _freeze(options.get("label_hard") or {}),
                          _freeze(options.get("label_soft") or {}),
                          _freeze(venv_desc)
                          if venv_desc is not None else None)
        task = PendingTask(
            task_id=task_id.binary(), header=header, blobs=blobs,
            return_ids=return_ids, retries_left=max(0, retries),
            retry_exceptions=bool(options.get("retry_exceptions")),
            scheduling_key=scheduling_key, borrowed=borrowed)
        refs = [ObjectRef(rid, self.address) for rid in return_ids]
        if options.get("streaming"):
            self._ret0_task_ids[return_ids[0]] = task_id.binary()
        with self._ref_lock:
            for rid in return_ids:
                rec = self.owned.setdefault(rid, OwnedObject())
                rec.local_refs += 1
                rec.submit_spec = (fid, header, blobs, scheduling_key)
                rec.retries_left = max(0, retries)
        fn_name = getattr(fn, "__qualname__",
                          getattr(fn, "__name__", fid[:12]))
        if memledger.ENABLED:
            # The submitted function IS the callsite that groups task
            # returns in `ray memory` (ray: "(task call) fn" rows).
            for rid in return_ids:
                memledger.note_create(rid, "task_return",
                                      "(task) " + fn_name)

        def _go():
            self.memory_entries_for(return_ids)
            self.lease_manager.submit(task)

        self._post_to_loop(_go)
        # The submitted TASK's trace context (not this process's current
        # one): its span_id/parent_span are what the OTLP bridge pairs.
        # Readable function name, not the fid hash — summarize_tasks
        # groups (and the timeline labels) by it.
        self._record_event(task_id.hex(), "SUBMITTED", fn_name,
                           trace=header["trace"])
        return refs

    def memory_entries_for(self, return_ids: list[bytes]) -> None:
        for rid in return_ids:
            self.memory.entry(rid)

    # ------------------------------------------------ streaming generators
    def submit_streaming_task(self, fn: Any, args: tuple, kwargs: dict,
                              options: dict):
        """Submit a generator task whose items stream back as they are
        produced (ray: streaming ObjectRefGenerator).  Returns the
        generator immediately — no waiting for the task."""
        from ray_tpu.object_ref import StreamingObjectRefGenerator

        options = {**options, "num_returns": 1, "streaming": True}
        refs = self.submit_task(fn, args, kwargs, options)
        return StreamingObjectRefGenerator(
            self._task_id_of(refs[0]), refs[0], self)

    def submit_streaming_actor_task(self, actor_id: str, method: str,
                                    args: tuple, kwargs: dict,
                                    options: dict):
        from ray_tpu.object_ref import StreamingObjectRefGenerator

        options = {**options, "num_returns": 1, "streaming": True}
        refs = self.submit_actor_task(actor_id, method, args, kwargs,
                                      options)
        return StreamingObjectRefGenerator(
            self._task_id_of(refs[0]), refs[0], self)

    def _task_id_of(self, ref: ObjectRef) -> bytes:
        """task_id for a return-0 ref minted by this process this session
        (submit paths record it)."""
        return self._ret0_task_ids.pop(ref.binary())

    def _stream_state(self, task_id: bytes) -> StreamState:
        st = self.streams.get(task_id)
        if st is None:
            st = StreamState()
            self.streams[task_id] = st
        return st

    def stream_next(self, task_id: bytes, index: int,
                    timeout: float | None = None) -> ObjectRef:
        """Blocking wait for item `index` of a streaming task.  Raises
        StopAsyncIteration past the end, or the task's error."""
        return self.run(self._stream_next_async(task_id, index), timeout)

    async def _stream_next_async(self, task_id: bytes,
                                 index: int) -> ObjectRef:
        st = self._stream_state(task_id)
        while True:
            if index < len(st.refs):
                return st.refs[index]
            if st.total is not None and index >= st.total:
                if st.error is not None:
                    # Copy: re-raising the STORED exception would grow its
                    # traceback in place and pin this caller's frames for
                    # the stream state's lifetime (see _copy_error).
                    raise _copy_error(st.error)
                raise StopAsyncIteration
            st.event.clear()
            await st.event.wait()

    def drop_stream(self, task_id: bytes) -> None:
        """Generator finalizer hook: forget the stream state (item refs
        release via their own ObjectRef finalizers) and tombstone the
        stream so late items are refused."""
        def _drop():
            self.streams.pop(task_id, None)
            self._dead_streams.add(task_id)
            self._dead_stream_order.append(task_id)
            while len(self._dead_stream_order) > 4096:
                self._dead_streams.discard(self._dead_stream_order.pop(0))
        try:
            self._post_to_loop(_drop)
        except RuntimeError:
            pass    # loop gone at teardown: nothing to clean

    async def rpc_stream_item(self, h: dict, blobs: list) -> dict:
        """Owner-side registration of one streamed item (the executing
        worker awaits this ack — that is the stream's backpressure AND the
        guarantee that every item is registered before the final task
        reply arrives)."""
        task_id = bytes.fromhex(h["task_id"])
        if task_id in self._dead_streams:
            # Consumer abandoned the stream: refuse the item so nothing
            # pins it (the producer keeps its retry budget intact; the
            # final reply cleans up the return-0 record).
            return {}
        st = self._stream_state(task_id)
        index = h["index"]
        tid = TaskID(task_id)
        iid = ObjectID.for_return(tid, index + 1).binary()
        if memledger.ENABLED:
            memledger.note_create(iid, "task_return", "(stream item)")
        with self._ref_lock:
            irec = self.owned.setdefault(iid, OwnedObject())
            prev_pins, irec.contained = irec.contained, [
                (bytes.fromhex(c[0]), c[1]) for c in h.get("contained", ())]
            rec0 = self.owned.get(ObjectID.for_return(tid, 0).binary())
            if rec0 is not None:
                irec.submit_spec = rec0.submit_spec
                irec.retries_left = rec0.retries_left
            irec.size = h.get("size", 0)
            if h.get("inline"):
                irec.state = "inline"
                irec.frames = list(blobs)
                self.memory.put_frames(iid, irec.frames)
            else:
                irec.state = "stored"
                irec.locations = [h["location"]]
                self.memory.put_locations(iid, irec.locations)
            if index >= len(st.refs):
                # One count for the ObjectRef held in the stream (handed
                # to the consumer by stream_next).
                irec.local_refs += 1
                st.refs.append(ObjectRef(iid, self.address))
            # else: a retried task re-shipped an index we already hold —
            # payload refreshed above, no new ref/pin.
        for c_oid, c_owner in prev_pins:
            self._release_borrow(c_oid, c_owner)
        st.event.set()
        return {}

    def _finish_stream(self, task: PendingTask, reply: dict,
                       blobs: list) -> None:
        """Owner-side handling of a streaming task's final reply: resolve
        the return-0 ref to an ObjectRefGenerator over all items (dynamic
        compat — the items are pinned as its contained refs) and wake
        consumers."""
        from ray_tpu.object_ref import ObjectRefGenerator

        st = self._stream_state(task.task_id)
        abandoned = task.task_id in self._dead_streams
        status = reply.get("status")
        total = int(reply.get("streamed", 0))
        rid0 = task.return_ids[0]
        if status == "ok":
            prev_contained: list = []
            rec = None
            with self._ref_lock:
                rec = self.owned.get(rid0)
                contained = []
                for ref in st.refs[:total]:
                    iid = ref.binary()
                    irec = self.owned.get(iid)
                    if irec is not None:
                        irec.borrowers += 1
                        contained.append((iid, self.address))
                value = ObjectRefGenerator(list(st.refs[:total]))
                sv = serialize(value)
                if rec is None:
                    tmp = OwnedObject()
                    tmp.contained = contained
                    self._free_object(rid0, tmp)
                else:
                    prev_contained, rec.contained = rec.contained, contained
                    rec.state = "inline"
                    rec.frames = sv.frames
                    e = self.memory.entry(rid0)
                    e.frames = sv.frames
                    e.has_value, e.value = True, value
                    e.wake()
            for c_oid, c_owner in prev_contained:
                self._release_borrow(c_oid, c_owner)
            st.total = total
            self._record_event(task.task_id.hex(), "FINISHED",
                               trace=task.header.get("trace"))
        elif status == "cancelled":
            st.error = TaskCancelledError(task.task_id.hex())
            st.total = total
            self._resolve_error(rid0, st.error)
        else:
            exc, tb = None, reply.get("traceback", "")
            if blobs:
                try:
                    import pickle

                    exc = pickle.loads(blobs[0])
                except Exception:  # noqa: BLE001
                    exc = RuntimeError("task failed")
            if task.retry_exceptions and task.retries_left > 0:
                task.retries_left -= 1
                self.lease_manager.submit(task)
                return
            st.error = TaskError(exc or RuntimeError("task failed"), tb)
            st.total = total
            self._resolve_error(rid0, st.error)
            self._record_event(task.task_id.hex(), "FAILED",
                               trace=task.header.get("trace"))
        st.event.set()
        if abandoned:
            # The state above was a transient re-creation (the consumer is
            # gone); drop it again so nothing stays pinned.
            self.streams.pop(task.task_id, None)

    def _build_task_payload(self, task_id: bytes, fid: str, args: tuple,
                            kwargs: dict, num_returns: int,
                            resources: dict, bundle_key: str | None,
                            options: dict) -> tuple[dict, list[bytes]]:
        # Top-level ObjectRef args are resolved to values worker-side before
        # execution (ray: DependencyResolver; nested refs stay refs).
        arg_refs: list[dict] = []
        borrowed: dict[bytes, str] = {}    # deduped per task
        if not args and not kwargs:
            # No-arg calls dominate ping/poll-style actor traffic; their
            # pickled payload is a constant — skip the serializer.
            frames = _empty_args_frames()
        else:
            plain_args: list[Any] = []
            for i, a in enumerate(args):
                if isinstance(a, ObjectRef):
                    arg_refs.append({"pos": i, "id": a.hex(),
                                     "owner": a.owner_addr or self.address})
                    plain_args.append(None)
                    borrowed.setdefault(a.binary(),
                                        a.owner_addr or self.address)
                else:
                    plain_args.append(a)
            sv = serialize((tuple(plain_args), kwargs))
            # Snapshot zero-copy view frames: the push happens later on the
            # IO loop (and again on retry / lineage resubmit), so args must
            # have submission-time semantics — a caller mutating its array
            # after .remote() must not corrupt the task (ray: by-value arg
            # copies).
            frames = [f.tobytes() if isinstance(f, memoryview) else f
                      for f in sv.frames]
            for ref in sv.contained_refs:
                borrowed.setdefault(ref.binary(),
                                    ref.owner_addr or self.address)
            for oid, owner in borrowed.items():
                self._add_borrow(oid, owner)
        # Trace context priority: an OPEN flight-recorder span (contextvar
        # — set by library spans and by async actor handlers, which never
        # touch the process-global current_trace) beats the executing
        # task's header; outside both, the submission roots a new trace.
        tc = spans.task_trace_context() or self.current_trace
        header = {
            "task_id": task_id.hex(), "function_id": fid,
            "num_returns": num_returns, "resources": resources,
            "owner_addr": self.address, "arg_refs": arg_refs,
            "bundle_key": bundle_key,
            "name": options.get("name", ""),
            # Job context: the driver's address travels with every task
            # (transitively through nested submissions), so driver-scoped
            # resources created INSIDE workers — placement groups above
            # all — are owned by the job's driver, not by a pooled worker
            # process whose exit would reap them (ray: PGs are job-scoped).
            "driver_addr": self.driver_addr,
            # W3C-style propagation: a task submitted INSIDE a task
            # continues its trace; a driver submission roots a new one
            # (trace_id = root task id).  span_id = this task's id.
            "trace": {
                "trace_id": tc["trace_id"] if tc else task_id.hex(),
                "parent_span": tc["span_id"] if tc else None,
                "span_id": task_id.hex(),
            },
        }
        if options.get("dynamic"):
            header["dynamic"] = True
        if options.get("streaming"):
            header["streaming"] = True
        if options.get("runtime_env"):
            from ray_tpu._private import runtime_env as renv

            header["runtime_env"] = renv.prepare(
                options["runtime_env"], self)
            if header["runtime_env"].get("venv") is not None \
                    and resources.get("TPU", 0) > 0:
                # The device worker is a per-host singleton on the
                # agent's interpreter; it cannot be respawned per env.
                raise ValueError(
                    "venv runtime_env is unsupported for TPU "
                    "tasks/actors: the device worker owns the chip and "
                    "cannot run an isolated interpreter (use pip/"
                    "py_modules kinds instead)")
        if options.get("affinity_node_id"):
            header["affinity_node_id"] = options["affinity_node_id"]
            header["affinity_soft"] = options.get("affinity_soft", False)
        if options.get("label_hard"):
            header["label_hard"] = options["label_hard"]
        if options.get("label_soft"):
            header["label_soft"] = options["label_soft"]
        return header, frames, list(borrowed.items())

    def _add_borrow(self, oid: bytes, owner_addr: str) -> None:
        if owner_addr == self.address or not owner_addr:
            with self._ref_lock:
                rec = self.owned.get(oid)
                if rec:
                    rec.borrowers += 1
        else:
            async def _notify():
                try:
                    await self.clients.get(owner_addr).notify(
                        "add_borrow", {"object_id": oid.hex()})
                except Exception:  # noqa: BLE001
                    pass
            self._post_to_loop(lambda: self.loop.create_task(_notify()))

    def _release_borrow(self, oid: bytes, owner_addr: str) -> None:
        """Undo one _add_borrow pin (submitter after reply, or borrower
        dropping a still-held ref)."""
        if owner_addr == self.address or not owner_addr:
            with self._ref_lock:
                rec = self.owned.get(oid)
                if rec:
                    rec.borrowers -= 1
                    if rec.local_refs <= 0 and rec.borrowers <= 0:
                        self._free_object(oid, rec)
        else:
            async def _notify():
                try:
                    await self.clients.get(owner_addr).notify(
                        "remove_borrow", {"object_id": oid.hex()})
                except Exception:  # noqa: BLE001
                    pass
            self._post_to_loop(lambda: self.loop.create_task(_notify()))

    def _release_task_borrows(self, task: "PendingTask") -> None:
        """Release this task's submission pins.  By reply time the
        executing worker has registered its own borrows for any arg refs it
        still holds (deserialize-time registration, _register_borrows), so
        the submission pins are pure transfer-window protection."""
        for oid, owner in task.borrowed:
            self._release_borrow(oid, owner)
        task.borrowed = []

    def _dedup_contained(self, contained_refs: list) -> list[tuple]:
        """Unique (object_id, owner) pairs for refs nested in one value."""
        seen: set[bytes] = set()
        out: list[tuple] = []
        for r in contained_refs:
            oid = r.binary()
            if oid in seen:
                continue
            seen.add(oid)
            out.append((oid, r.owner_addr or self.address))
        return out

    async def _register_borrows(self, refs: list) -> None:
        """Deserialize-time borrower registration (ray: reference_count.cc
        borrower bookkeeping): this process counts local instances of refs
        it does not own; the first instance registers with the owner, the
        last drop (in _release_local_ref) sends remove_borrow.  Awaited
        BEFORE the value is used so the registration lands while the
        sender's pin (submission pin / contained pin) still protects the
        object."""
        to_ack: list[tuple[bytes, str]] = []
        with self._ref_lock:
            for r in refs:
                oid = r.binary()
                owner = r.owner_addr
                if not owner or owner == self.address:
                    continue    # own refs are counted via local_refs
                entry = self.borrows.get(oid)
                if entry is not None:
                    entry["count"] += 1
                    continue
                self.borrows[oid] = {"count": 1, "owner": owner,
                                     "acked": False}
                to_ack.append((oid, owner))
        if not to_ack:
            return
        # Concurrent acks: one round-trip/timeout total, not one per owner.
        for oid, _owner in await self._pin_remote(to_ack):
            with self._ref_lock:
                entry = self.borrows.get(oid)
                if entry is not None:
                    entry["acked"] = True

    async def _pin_remote(self, pairs: list[tuple[bytes, str]]
                          ) -> list[tuple[bytes, str]]:
        """add_borrow each (object_id, owner) with an ack; return the pairs
        whose ack landed.  A failed/timed-out ack counts as NOT pinned and
        its matching release must be skipped: if the add actually landed we
        leak one borrow (object lives too long), never undercount and free
        an object another borrower still holds."""
        acked: list[tuple[bytes, str]] = []

        async def _one(oid: bytes, owner: str) -> None:
            try:
                reply, _ = await self.clients.get(owner).call(
                    "add_borrow", {"object_id": oid.hex()}, timeout=10.0)
                acked.append((oid, owner))
            except Exception:  # noqa: BLE001 - owner may already be gone
                return
            # Location hint riding the ack (see rpc_add_borrow): prefill
            # the entry so the upcoming get() pulls straight from the
            # holding node with no resolve_object round trip.  Hints can
            # go stale (the owner may free/move the object) — _get_one
            # falls back to the authoritative owner resolve when a
            # hinted pull misses.
            if isinstance(reply, dict) and reply.get("state") == "stored":
                e = self.memory.entry(oid)
                if not e.resolved():
                    e.locations = list(reply.get("locations") or [])
                    if e.locations:
                        e.hinted = True
                        e.wake()
        await asyncio.gather(*[_one(o, w) for o, w in pairs])
        return acked

    # -------- task reply handling (owner side) --------
    def _on_task_reply(self, task: PendingTask, reply: dict,
                       blobs: list[bytes]) -> None:
        status = reply.get("status")
        if task.actor_state is not None and not (
                status == "error" and task.retry_exceptions
                and task.retries_left > 0):
            # Terminal reply of an actor call: release its slot in the
            # submitter's unacked count (gates the fused sync fast path).
            # Exactly once — the direct path's IO-thread callback clears
            # actor_state before this runs.
            with task.actor_state.submit_lock:
                task.actor_state.unacked -= 1
            task.actor_state = None
        if status != "error" or not (task.retry_exceptions
                                     and task.retries_left > 0):
            # Terminal reply: drop submission borrow pins (retried tasks
            # keep theirs — the resend ships the same refs).
            self._release_task_borrows(task)
        if task.header.get("streaming"):
            self._finish_stream(task, reply, blobs)
            return
        if status == "ok":
            returns = reply["returns"]
            offset = 0
            for i, meta in enumerate(returns):
                rid = task.return_ids[i]
                if meta.get("dynamic") is not None:
                    offset = self._resolve_dynamic_return(
                        task, rid, meta, blobs, offset)
                    continue
                if meta["inline"]:
                    nframes = meta["nframes"]
                    frames = blobs[offset:offset + nframes]
                    offset += nframes
                else:
                    frames = None
                with self._ref_lock:
                    rec = self.owned.get(rid)
                    if rec is None:
                        # Return ref already dropped (fire-and-forget):
                        # don't resurrect the record — local_refs would
                        # stay 0 and the executor's contained pins would
                        # never release.  Free value + pins right away.
                        tmp = OwnedObject()
                        tmp.contained = [(bytes.fromhex(c[0]), c[1])
                                         for c in meta.get("contained", ())]
                        if not meta["inline"]:
                            tmp.locations = [meta["location"]]
                        self._free_object(rid, tmp)
                        continue
                    # A re-executed task (lineage reconstruction) re-pins
                    # its contained refs; release the previous round's
                    # pins first.
                    prev_contained, rec.contained = rec.contained, [
                        (bytes.fromhex(c[0]), c[1])
                        for c in meta.get("contained", ())]
                    rec.size = meta.get("size", 0)
                    if meta["inline"]:
                        rec.state = "inline"
                        rec.frames = frames
                        self.memory.put_frames(rid, frames)
                    else:
                        rec.state = "stored"
                        rec.locations = [meta["location"]]
                        self.memory.put_locations(rid, rec.locations)
                for c_oid, c_owner in prev_contained:
                    self._release_borrow(c_oid, c_owner)
            self._record_event(task.task_id.hex(), "FINISHED",
                               trace=task.header.get("trace"))
        elif status == "cancelled":
            err = TaskCancelledError(task.task_id.hex())
            for rid in task.return_ids:
                self._resolve_error(rid, err)
        else:
            exc, tb = None, reply.get("traceback", "")
            if blobs:
                try:
                    import pickle
                    exc = pickle.loads(blobs[0])
                except Exception:  # noqa: BLE001
                    exc = RuntimeError(reply.get("error", "task failed"))
            if task.retry_exceptions and task.retries_left > 0:
                task.retries_left -= 1
                self.lease_manager.submit(task)
                return
            err = TaskError(exc or RuntimeError("task failed"), tb)
            for rid in task.return_ids:
                self._resolve_error(rid, err)
            self._record_event(task.task_id.hex(), "FAILED",
                               trace=task.header.get("trace"))

    def _resolve_dynamic_return(self, task: PendingTask, rid: bytes,
                                meta: dict, blobs: list,
                                offset: int) -> int:
        """Materialize a dynamic-generator reply: one owned record per
        yielded item (the caller owns items exactly like fixed returns),
        and the return-0 value becomes an ObjectRefGenerator.  The
        return-0 record pins every item (contained refs), so items live
        while the generator object does."""
        from ray_tpu.object_ref import ObjectRefGenerator

        tid = TaskID(task.task_id)
        rid0 = ObjectID.for_return(tid, 0).binary()
        # Lineage reconstruction of a lost ITEM resubmits the task with
        # return_ids=[item_id]: the reply then restores item payloads
        # only — rid is NOT the generator's return-0, so the generator
        # value/pins must not be rebuilt onto the item's record.
        item_reconstruction = rid != rid0
        gen_refs: list[ObjectRef] = []
        contained: list[tuple[bytes, str]] = []
        prev_item_pins: list[tuple[bytes, str]] = []
        prev_contained: list[tuple[bytes, str]] = []
        with self._ref_lock:
            rec = self.owned.get(rid)
            for j, im in enumerate(meta["dynamic"]):
                iid = ObjectID.for_return(tid, j + 1).binary()
                irec = self.owned.setdefault(iid, OwnedObject())
                if memledger.ENABLED:
                    memledger.note_create(iid, "task_return",
                                          "(generator item)")
                # Pins for refs nested in the item value (re-execution
                # releases the previous round's, as in the fixed path).
                prev_item_pins.extend(irec.contained)
                irec.contained = [(bytes.fromhex(c[0]), c[1])
                                  for c in im.get("contained", ())]
                # Items share the task's lineage: losing one re-runs the
                # whole generator task (same deterministic item ids).
                if rec is not None:
                    irec.submit_spec = rec.submit_spec
                    irec.retries_left = rec.retries_left
                irec.size = im.get("size", 0)
                if im["inline"]:
                    n = im["nframes"]
                    irec.state = "inline"
                    irec.frames = blobs[offset:offset + n]
                    self.memory.put_frames(iid, irec.frames)
                    offset += n
                else:
                    irec.state = "stored"
                    irec.locations = [im["location"]]
                    self.memory.put_locations(iid, irec.locations)
                if not item_reconstruction:
                    # One count for the live ObjectRef handed out below,
                    # one pin owned by the return-0 record.
                    irec.local_refs += 1
                    irec.borrowers += 1
                    contained.append((iid, self.address))
                    gen_refs.append(ObjectRef(iid, self.address))
            if not item_reconstruction:
                value = ObjectRefGenerator(gen_refs)
                sv = serialize(value)  # for remote resolvers of return-0
                if rec is None:
                    # Return ref dropped already: release the pins right
                    # away (the live gen_refs die with this frame).
                    tmp = OwnedObject()
                    tmp.contained = contained
                    self._free_object(rid, tmp)
                else:
                    prev_contained, rec.contained = rec.contained, \
                        contained
                    rec.state = "inline"
                    rec.frames = sv.frames
                    e = self.memory.entry(rid)
                    e.frames = sv.frames
                    e.has_value, e.value = True, value
                    e.wake()
        for c_oid, c_owner in prev_contained:
            self._release_borrow(c_oid, c_owner)
        for c_oid, c_owner in prev_item_pins:
            self._release_borrow(c_oid, c_owner)
        return offset

    def _service_entry_from_owned(self, oid: bytes, e) -> bool:
        """Lost-wake recovery: if this process's owner record for `oid`
        has resolved but the memory entry never woke (fill/wake race),
        republish the fill through the store (which wakes both waiter
        kinds).  Returns True when the entry is now resolvable."""
        rec = self.owned.get(oid)
        if rec is None or rec.state == "pending":
            return False
        with self._ref_lock:
            rec = self.owned.get(oid)
            if rec is None or rec.state == "pending":
                return False
            if e.resolved():
                # Fields landed but a set() was missed — just re-wake.
                e.wake()
            elif rec.state == "error" and rec.error is not None:
                self.memory.put_error(oid, rec.error)
            elif rec.state == "inline" and rec.frames is not None:
                self.memory.put_frames(oid, rec.frames)
            elif rec.state == "stored" and rec.locations:
                self.memory.put_locations(oid, rec.locations)
            else:
                return False
        logger.warning("recovered lost fill for %s (owner state=%s)",
                       oid.hex()[:12], rec.state)
        return True

    def _resolve_error(self, rid: bytes, err: BaseException) -> None:
        rec = self.owned.get(rid)
        if rec is None:
            # Ref already dropped before resolution — nobody can observe
            # the error; don't resurrect a record that can never be freed.
            return
        rec.state = "error"
        rec.error = err
        self.memory.put_error(rid, err)

    # ------------------------------------------------------------- get/put
    def local_arena(self):
        """The mmap'd local node store, or None (dict backend / remote
        agent / native build unavailable).  Serialized: the startup
        warm thread and the first put/get race here, and a half-open
        arena must never be visible (a losing racer would silently take
        the agent-RPC slow path)."""
        if not self._arena_tried:
            with self._arena_lock:
                if not self._arena_tried:
                    if self.store_name:
                        try:
                            from ray_tpu._private import native_store

                            # A zygote-forked worker inherits the pre-
                            # warmed mapping (PTEs populated pre-fork):
                            # reuse it instead of re-mapping + re-
                            # prefaulting 512MB per process.
                            arena = native_store.take_prefork_arena(
                                self.store_name)
                            if arena is not None:
                                arena.retune(
                                    self.config.put_stream_min_bytes,
                                    self.config.put_parallel_min_bytes)
                            else:
                                arena = native_store.Arena(
                                    self.store_name,
                                    stream_min=(
                                        self.config.put_stream_min_bytes),
                                    parallel_min=(
                                        self.config.put_parallel_min_bytes))
                            self._arena = arena
                        except Exception as e:  # noqa: BLE001 - RPC fallback
                            self._arena = None
                            self._note_arena_fallback(
                                f"arena map failed: {e!r}", count=False)
                    self._arena_tried = True
        return self._arena

    def warm_arena(self) -> None:
        """Map the arena, then write-prefault this process's PTEs over
        its free space (claim/touch/abort — native_store.prefault_free).
        A concurrent warmer in another process holds the claims while it
        touches, so retry briefly before giving up: an unwarmed process
        pays a write-protect fault per page on its first bulk put."""
        arena = self.local_arena()
        if arena is None:
            return
        if getattr(arena, "prewarmed", False):
            # Zygote-inherited mapping: PTEs were populated pre-fork —
            # a second claim/touch pass would only contend the arena
            # mutex with 23 sibling workers doing the same no-op.
            return
        for attempt in range(3):
            try:
                if arena.prefault_free() or attempt == 2:
                    return
            except Exception:  # noqa: BLE001 - prefault is best-effort
                return
            time.sleep(0.1 * (attempt + 1))

    def _note_arena_fallback(self, cause: str, count: bool = True) -> None:
        """Record (and log ONCE per process) why large puts are not
        writing straight into the mmap'd arena."""
        if count:
            self._arena_fallbacks += 1
        if self._arena_fallback_cause is None:
            self._arena_fallback_cause = cause
            logger.warning(
                "large put falling back to the agent store_put RPC "
                "(first cause: %s) — arena-direct puts disabled or "
                "degraded in this process", cause)

    def _store_frames_local(self, oid: bytes, frames: list,
                            trace: dict | None = None) -> bool:
        """Write frames into the local node store, zero-RPC when the arena
        is mapped; falls back to the agent store_put RPC.  Every fallback
        is counted and its first cause logged (profiling.put_stats)."""
        arena = self.local_arena()
        if arena is None:
            self._note_arena_fallback(
                "arena unmapped"
                + ("" if self.store_name else " (agent reported no shm "
                   "store — native build unavailable?)"))
            return False
        try:
            if arena.put_frames(oid, frames, trace=trace):
                self._arena_puts += 1
                return True
        except Exception as e:  # noqa: BLE001
            self._note_arena_fallback(f"arena put raised: {e!r}")
            return False
        self._note_arena_fallback(
            "arena refused put (full or duplicate id); stats=%s"
            % (arena.stats(),))
        return False

    def put_object(self, value: Any) -> ObjectRef:
        from ray_tpu._private import profiling

        trace = profiling.consume_put_arm()
        t_span0 = time.time() if spans.ENABLED else 0.0
        oid = ObjectID.for_put(WorkerID.from_hex(self.worker_id),
                               next(self._put_seq)).binary()
        sv = serialize(value)
        if trace is not None:
            trace["serialize_done"] = time.monotonic()
            trace["bytes"] = sv.total_bytes
        with self._ref_lock:
            rec = self.owned.setdefault(oid, OwnedObject())
            rec.local_refs += 1
            rec.size = sv.total_bytes
            # Contained pins for refs nested in the value (released when
            # this object is freed).  Fire-and-forget notify suffices here
            # (unlike _pack_returns): this process's later remove_borrow
            # rides the same owner connection, so the add is ordered
            # before it.
            for c_oid, owner in self._dedup_contained(sv.contained_refs):
                rec.contained.append((c_oid, owner))
                self._add_borrow(c_oid, owner)
        if trace is not None:
            trace["owner_reg_done"] = time.monotonic()
        if memledger.ENABLED:
            memledger.note_put(oid)
        put_path = "inline"
        if sv.total_bytes <= self.config.max_inline_object_size:
            if trace is not None:
                trace["path"] = "inline"
            rec.state = "inline"
            rec.frames = sv.frames
            # Fields publish synchronously (the get fast path reads them
            # from the caller's thread, GIL-ordered); only the asyncio
            # event must be set on the loop.
            e = self.memory.entry(oid)
            e.has_value, e.value = True, value
            e.frames = sv.frames
            # Coalesced wake: a burst of puts costs ONE self-pipe write
            # (call_soon_threadsafe per put made the loop thread do a
            # pipe read + GIL trade per object — the dominant cost of
            # put-heavy loops).
            self._post_to_loop(e.wake)
        elif self._store_frames_local(oid, sv.frames, trace=trace):
            # Zero-RPC path: wrote straight into the mmap'd arena from the
            # caller's thread.
            # Failpoint window: the object is SEALED in the arena but the
            # owner record has not published it yet — a crash here orphans
            # a sealed object whose owner never existed.
            if failpoints.ACTIVE:
                failpoints.fire("put.publish")
            put_path = "arena"
            if trace is not None:
                trace["path"] = "arena"
            rec.state = "stored"
            rec.locations = [self.agent_addr]
            e = self.memory.entry(oid)
            e.has_value, e.value = True, value
            self._post_to_loop(e.wake)
        else:
            put_path = "rpc"
            if trace is not None:
                trace["path"] = "rpc"

            async def _store():
                reply, _ = await self.clients.get(self.agent_addr).call(
                    "store_put", {"object_id": oid.hex()}, sv.frames)
                rec.state = "stored"
                rec.locations = [self.agent_addr]
                e = self.memory.entry(oid)
                e.has_value, e.value = True, value
                e.wake()
            self.run(_store())
            if trace is not None:
                trace["store_rpc_done"] = time.monotonic()
        if trace is not None:
            trace["put_done"] = time.monotonic()
            profiling.publish_put_trace(trace)
        if spans.ENABLED and t_span0 and sv.total_bytes > \
                self.config.max_inline_object_size:
            # Arena/RPC puts only: inline puts are a dict move, and a
            # span per tiny put would churn the ring for nothing.  The
            # t_span0 guard (here and at every task-span site) skips
            # work that started before a LIVE recorder flip — an
            # epoch-0 t0 would corrupt the merged timeline.
            spans.emit("arena.put", t_span0,
                       attrs={"bytes": sv.total_bytes,
                              "path": put_path})
        return ObjectRef(oid, self.address)

    _GET_MISS = object()

    def get_objects(self, refs: list[ObjectRef],
                    timeout: float | None = None) -> list[Any]:
        if len(refs) == 1 and self._sync_calls:
            # get-after-submit of a fused sync actor call: bind to the
            # in-flight reply future and wake straight from the IO
            # thread (the submit side already skipped the loop).
            sc = self._sync_calls.pop(refs[0].binary(), None)
            if sc is not None:
                out = self._finish_sync_call(refs[0], sc, timeout)
                if out is not CoreWorker._GET_MISS:
                    return [out]
        out = self._get_objects_fast(refs, timeout)
        if out is not CoreWorker._GET_MISS:
            return out
        return self.run(self._get_objects_async(refs, timeout))

    def _get_objects_fast(self, refs: list[ObjectRef],
                          timeout: float | None):
        """Resolve a batch in the CALLING thread when every ref is owned
        here and resolves from the in-process store — no coroutine per
        ref, no IO-loop round trip (the loop's scheduling jitter was the
        dominant cost of bulk gets of local objects).  Pending entries
        wait on a lazily-attached threading.Event that every fill site
        signals via MemoryEntry.wake().  Falls back to the async path
        for borrowed refs, arena-stored objects, and values containing
        ObjectRefs (borrow registration needs the loop)."""
        import threading

        MISS = CoreWorker._GET_MISS
        entries = []
        for r in refs:
            oid = r.binary()
            if not (oid in self.owned or r.owner_addr in ("",
                                                          self.address)):
                return MISS
            entries.append(self.memory.entry(oid))
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        out = []
        for r, e in zip(refs, entries):
            if not e.resolved():
                if e.t_event is None:
                    # CAS under the store lock: two concurrent getters
                    # must share ONE event (an overwrite would orphan
                    # the first waiter).
                    with self.memory._lock:
                        if e.t_event is None:
                            e.t_event = threading.Event()
                # Re-check AFTER publishing t_event: a fill between our
                # check and the attach would have missed it.
                if not e.resolved():
                    if deadline is None:
                        # NEVER wait unbounded here: the fast path has no
                        # failure-event machinery, so any lost fill (actor
                        # death races, reconstruction) would hang the
                        # caller forever.  After a grace period, hand the
                        # wait to the async path, which resolves through
                        # owners and observes death/lineage events.
                        if not e.t_event.wait(5.0):
                            logger.warning(
                                "sync get slow for %s; falling back to "
                                "the async resolution path", r.hex()[:12])
                            return MISS
                    elif not e.t_event.wait(
                            max(0.0, deadline - time.monotonic())):
                        raise GetTimeoutError(
                            f"get() timed out waiting for "
                            f"{r.hex()[:12]}")
            if e.error is not None:
                raise _copy_error(e.error)
            if e.has_value:
                out.append(e.value)
                continue
            if e.frames is not None:
                value, contained = deserialize_with_refs(e.frames)
                if contained:
                    return MISS
                e.has_value, e.value = True, value
                out.append(value)
                continue
            return MISS   # arena locations / unresolved: loop path
        return out

    async def _get_objects_async(self, refs: list[ObjectRef],
                                 timeout: float | None) -> list[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        results = await asyncio.gather(
            *[self._get_one(r, deadline) for r in refs])
        out = []
        for r in results:
            if isinstance(r, BaseException):
                raise _copy_error(r)
            out.append(r)
        return out

    async def _deserialize_registering(self, frames) -> Any:
        """Materialize a value, registering this process as a borrower of
        any refs nested inside it (see _register_borrows)."""
        # Small payloads deserialize inline: a thread-pool hop costs more
        # (queue wakeup + context switch, ~0.2ms) than the pickle itself.
        if sum(len(f) for f in frames) <= self.config.max_inline_object_size:
            value, contained = deserialize_with_refs(frames)
        else:
            value, contained = await self.loop.run_in_executor(
                None, deserialize_with_refs, frames)
        if contained:
            await self._register_borrows(contained)
        return value

    async def _get_one(self, ref: ObjectRef, deadline: float | None) -> Any:
        e = self.memory.get_if_exists(ref.binary())
        owned_here = ref.binary() in self.owned or ref.owner_addr in (
            "", self.address)
        if e is None and owned_here:
            e = self.memory.entry(ref.binary())
        if e is not None:
            # Bounded-slice wait + watchdog instead of one unbounded
            # event wait: the owner record (self.owned) is the truth, and
            # a fill whose wake was lost in a race (observed once on the
            # bench box as a 600s wedge, BENCH_r04) would otherwise hang
            # this coroutine forever.  Every slice re-checks the record
            # and self-services a resolved-but-unwoken entry; a record
            # stuck "pending" is logged with its state so a real wedge
            # names itself in the process tail.
            waited = 0.0
            while not e.event.is_set():
                remaining = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                slice_t = 10.0 if remaining is None \
                    else min(10.0, remaining)
                try:
                    await asyncio.wait_for(e.event.wait(), slice_t)
                    break
                except asyncio.TimeoutError:
                    if remaining is not None and remaining <= slice_t:
                        raise GetTimeoutError(
                            f"get() timed out waiting for "
                            f"{ref.hex()[:12]}")
                    waited += slice_t
                    if self._service_entry_from_owned(ref.binary(), e):
                        break
                    if waited >= 30.0 and int(waited) % 30 < 10:
                        rec = self.owned.get(ref.binary())
                        logger.warning(
                            "get() still waiting for %s after %.0fs "
                            "(owner record: %s)", ref.hex()[:12], waited,
                            "absent" if rec is None else rec.state)
            if e.error is not None:
                return e.error
            if e.has_value:
                return e.value
            if e.frames is not None:
                value = await self._deserialize_registering(e.frames)
                e.has_value, e.value = True, value
                return value
            if e.locations:
                value = await self._pull_and_load(ref, e.locations, e)
                if not (isinstance(value, ObjectLostError)
                        and getattr(e, "hinted", False)
                        and not owned_here):
                    return value
                # A piggybacked location hint (borrow-ack fast path)
                # went stale — the owner may have moved/freed and
                # re-created state we don't see.  Clear it and ask the
                # owner authoritatively.
                e.locations = []
                e.hinted = False
            # fallthrough: resolved elsewhere
        return await self._get_from_owner(ref, deadline)

    async def _get_from_owner(self, ref: ObjectRef,
                              deadline: float | None) -> Any:
        if ref.owner_addr in self._dead_worker_addrs:
            # Known-dead owner: resolving would hang on a reconnecting
            # DEALER; the object is lost with its owner (put objects
            # have no lineage; task returns resubmit via their OWN owner).
            from ray_tpu.exceptions import OwnerDiedError

            return OwnerDiedError(
                ref.hex(),
                f"object {ref.hex()[:12]}: owner {ref.owner_addr} died "
                f"with the authoritative copy; put/borrowed objects have "
                f"no lineage, so reconstruction was not attempted")
        remaining = None if deadline is None \
            else max(0.0, deadline - time.monotonic())
        try:
            reply, blobs = await self.clients.get(ref.owner_addr).call(
                "resolve_object", {"object_id": ref.hex(), "wait": True},
                timeout=remaining)
        except asyncio.TimeoutError:
            raise GetTimeoutError(ref.hex()[:12])
        except (ConnectionLost, RemoteError) as err:
            return ObjectLostError(
                ref.hex(),
                f"object {ref.hex()[:12]}: owner {ref.owner_addr} "
                f"unreachable ({err}); lineage lives with the owner, so "
                f"reconstruction was not attempted")
        state = reply.get("state")
        if state == "inline":
            value = await self._deserialize_registering(blobs)
            e = self.memory.entry(ref.binary())
            e.has_value, e.value = True, value
            e.wake()
            return value
        if state == "error":
            import pickle
            return pickle.loads(blobs[0])
        if state == "stored":
            e = self.memory.entry(ref.binary())
            return await self._pull_and_load(ref, reply["locations"], e)
        return ObjectLostError(
            ref.hex(),
            f"object {ref.hex()[:12]}: owner {ref.owner_addr} no longer "
            f"holds it (state={state!r}); borrowed objects have no "
            f"lineage, so reconstruction was not attempted")

    async def _shm_name_of(self, addr: str) -> str | None:
        """The shm arena name behind a node agent addr, cached forever
        (an agent's arena never changes).  None = not native backend or
        meta unreachable (cached only on a definitive answer)."""
        if addr in self._peer_shm:
            return self._peer_shm[addr]
        try:
            st, _ = await self.clients.get(addr).call(
                "store_stats", {}, timeout=10.0)
        except Exception:  # noqa: BLE001 - don't cache a transient miss
            return None
        shm = st.get("shm_name") if isinstance(st, dict) else None
        self._peer_shm[addr] = shm
        return shm

    async def _pull_direct_shm(self, ref: ObjectRef, locations: list[str],
                               arena0) -> bool:
        """Same-host fast path: map the SOURCE node's /dev/shm arena and
        stream the sealed bundle straight into the local arena — no
        agent hop, no zmq, and (after the per-agent shm name is cached)
        zero control round trips per object.  The source-side read pin
        is the normal pid-attributed pin; a crashed puller is swept like
        any dead reader.  Kill switch RAY_TPU_SHM_PULL=0.

        Twin of StoreRunner._pull_same_host with a deliberately simpler
        failure policy: no spill-to-make-room and no wait-for-sibling —
        any create_raw refusal falls back to the agent path, which has
        both (keep the copy/seal/abort discipline in sync with it)."""
        if os.environ.get("RAY_TPU_SHM_PULL", "1") == "0":
            return False
        oid = ref.binary()
        for addr in locations:
            if addr in self._dead_worker_addrs:
                continue
            shm = await self._shm_name_of(addr)
            if not shm or not os.path.exists(
                    os.path.join("/dev/shm", shm.lstrip("/"))):
                continue
            peer = self._peer_arenas.get(shm)
            if peer is None:
                try:
                    from ray_tpu._private.native_store import Arena

                    peer = Arena(shm, create=False)
                except Exception:  # noqa: BLE001 - racing teardown
                    continue
                self._peer_arenas[shm] = peer
            raw = peer.get_raw_addr(oid)
            if raw is None:
                continue
            src_addr, size, release = raw
            try:
                if not arena0.create_raw(oid, size):
                    if arena0.contains(oid):
                        return True   # a sibling pull landed it already
                    # Full arena or another puller's in-flight creating
                    # block: the agent path handles both (spill to make
                    # room, wait-for-sibling in _reserve_raw).
                    return False
                def _copy() -> bool:
                    return arena0.write_raw_from_addr(oid, 0, src_addr,
                                                      size)
                ok = (await self.loop.run_in_executor(None, _copy)
                      if size > (8 << 20) else _copy())
                if ok:
                    ok = arena0.seal_raw(oid)
                    if ok:
                        return True
                arena0.abort_raw(oid)
                return False
            except BaseException:
                arena0.abort_raw(oid)
                raise
            finally:
                release()
        return False

    async def _pull_and_load(self, ref: ObjectRef, locations: list[str],
                             entry) -> Any:
        """Fetch frames from a node store holding the object."""
        arena0 = self.local_arena()
        if (arena0 is not None and locations
                and self.agent_addr not in locations):
            # Remote object + local arena: same-host sources are copied
            # straight out of THEIR mmap'd arena into ours (one
            # streaming-kernel copy, zero control round trips once the
            # source's shm name is cached — see _pull_direct_shm);
            # otherwise pull THROUGH the local node store (chunked,
            # parallel, cached for other local readers — ray: gets
            # always materialize into local plasma via the PullManager).
            # Either way the object lands locally and is read zero-copy.
            pulled = False
            try:
                pulled = await self._pull_direct_shm(ref, locations,
                                                     arena0)
            except Exception:  # noqa: BLE001 - fast path is best-effort
                pulled = False
            if not pulled:
                try:
                    reply, _ = await self.clients.get(
                        self.agent_addr).call(
                        "store_pull",
                        {"object_id": ref.hex(), "from": list(locations)},
                        timeout=300.0)
                    pulled = bool(reply.get("ok"))
                except Exception:  # noqa: BLE001
                    pulled = False
            if pulled:
                locations = [self.agent_addr] + list(locations)
                self._announce_location(ref)
        if self.agent_addr in locations:
            arena = self.local_arena()
            if arena is not None:
                # Zero-copy read: frames are memoryviews into the mmap'd
                # arena; the deserialized numpy/jax buffers alias shm
                # directly (ray: plasma client get + zero-copy numpy).
                frames = arena.get_frames(ref.binary())
                if frames is not None:
                    value = await self._deserialize_registering(frames)
                    entry.has_value, entry.value = True, value
                    entry.wake()
                    return value
        tried: list[str] = []
        for addr in locations:
            if addr in self._dead_worker_addrs:
                # Known-dead node/worker: a fresh DEALER would silently
                # reconnect-forever; skip straight to the next copy (or
                # lineage) instead of burning the RPC timeout.
                tried.append(f"{addr} (known dead)")
                continue
            try:
                reply, blobs = await self.clients.get(addr).call(
                    "store_get", {"object_id": ref.hex()}, timeout=120.0)
            except Exception as e:  # noqa: BLE001
                tried.append(f"{addr} ({type(e).__name__})")
                continue
            if reply.get("found"):
                value = await self._deserialize_registering(blobs)
                entry.has_value, entry.value = True, value
                entry.wake()
                return value
            tried.append(f"{addr} (not found)")
        # Every location failed: try lineage reconstruction.
        rec = self.owned.get(ref.binary())
        if rec and rec.submit_spec and rec.retries_left > 0:
            # Failpoint window: every copy is gone and the owner is about
            # to resubmit the producing task (crash = the getter dies
            # mid-reconstruction; error = reconstruction refused).
            if failpoints.ACTIVE:
                await failpoints.fire_async("worker.lineage_resubmit")
            rec.retries_left -= 1
            fid, header, blobs_, key = rec.submit_spec
            logger.warning("reconstructing %s via lineage", ref.hex()[:12])
            rec.state = "pending"
            # Reset IN PLACE: delete+recreate would orphan any waiter
            # holding the old entry object (its event would never fire
            # again — a permanent hang for sync fast-path getters).
            self.memory.reset(ref.binary())
            task = PendingTask(
                task_id=bytes.fromhex(header["task_id"]), header=header,
                blobs=blobs_, return_ids=[ref.binary()],
                retries_left=rec.retries_left, retry_exceptions=False,
                scheduling_key=key)
            self.lease_manager.submit(task)
            return await self._get_one(
                _UntrackedRef(ref.binary(), self.address), None)
        # Name the ref, the nodes tried, and the lineage verdict: a bare
        # object id gives an operator nothing to act on (the detail used
        # to stop at a log line here and the surfaced error lost it).
        if rec is not None and rec.submit_spec:
            lineage = "lineage reconstruction exhausted its retry budget"
        elif rec is not None:
            lineage = ("no lineage to reconstruct from (the object was "
                       "put(), not returned by a task)")
        else:
            lineage = ("not owned by this process, so no lineage is "
                       "available here")
        return ObjectLostError(
            ref.hex(),
            f"object {ref.hex()[:12]} lost: locations tried "
            f"{tried if tried else '(none known)'}; {lineage}")

    def wait(self, refs: list[ObjectRef], num_returns: int,
             timeout: float | None) -> tuple[list[ObjectRef], list[ObjectRef]]:
        return self.run(self._wait_async(refs, num_returns, timeout))

    async def _wait_async(self, refs, num_returns, timeout):
        async def _ready(ref: ObjectRef) -> ObjectRef:
            # Readiness must not deserialize or pull payloads: a timeout=0
            # poll cancels in-flight _ready tasks, so any await beyond the
            # entry event (e.g. run_in_executor deserialize) would make
            # polling never observe completion.  Errors count as ready
            # (like ray).
            e = self.memory.get_if_exists(ref.binary())
            if e is None and (ref.binary() in self.owned
                              or ref.owner_addr in ("", self.address)):
                e = self.memory.entry(ref.binary())
            if e is not None:
                await e.event.wait()
            else:
                await self._get_one(ref, None)   # remote owner: fetch local
            return ref

        tasks = {asyncio.ensure_future(_ready(r)): r for r in refs}
        done_refs: list[ObjectRef] = []
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = set(tasks)
        while pending and len(done_refs) < num_returns:
            remaining = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            done, pending = await asyncio.wait(
                pending, timeout=remaining,
                return_when=asyncio.FIRST_COMPLETED)
            if not done:
                break
            for d in done:
                done_refs.append(tasks[d])
        for p in pending:
            p.cancel()
        not_done = [r for r in refs if r not in done_refs]
        return done_refs, not_done

    def object_sizes(self, refs: list[ObjectRef]) -> list[int | None]:
        """Owner-table payload sizes for locally-owned refs (None when
        unknown/pending/not owned here).  Cheap: no payload fetch.  Feeds
        Data's resource-aware backpressure (ray: reference table sizes →
        data/_internal/execution/resource_manager.py)."""
        out: list[int | None] = []
        with self._ref_lock:
            for r in refs:
                rec = self.owned.get(r.binary())
                out.append(rec.size if rec is not None
                           and rec.state in ("inline", "stored")
                           and rec.size > 0 else None)
        return out

    def ref_future(self, ref: ObjectRef) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()

        async def _wait():
            try:
                v = await self._get_one(ref, None)
                if fut.done():
                    return   # consumer cancelled/abandoned the future
                if isinstance(v, BaseException):
                    fut.set_exception(_copy_error(v))
                else:
                    fut.set_result(v)
            except BaseException as e:  # noqa: BLE001
                try:
                    if not fut.done():
                        fut.set_exception(e)
                except concurrent.futures.InvalidStateError:
                    pass

        self.loop.call_soon_threadsafe(lambda: self.loop.create_task(_wait()))
        return fut

    # -------------------------------------------------------------- refcount
    def _release_local_ref(self, object_id: bytes) -> None:
        """ObjectRef.__del__ hook.  Owner-side: drop a local count.
        Borrower-side: the last local instance sends remove_borrow to the
        owner (ray: borrower removal path)."""
        with self._ref_lock:
            rec = self.owned.get(object_id)
            if rec is not None:
                rec.local_refs -= 1
                if rec.local_refs <= 0 and rec.borrowers <= 0:
                    self._free_object(object_id, rec)
                return
            entry = self.borrows.get(object_id)
            if entry is None:
                return
            entry["count"] -= 1
            if entry["count"] > 0:
                return
            self.borrows.pop(object_id, None)
        # Past the lock: the entry is detached, only this thread sees it.
        # Un-acked registration (owner unreachable at deserialize time): a
        # remove here would be unmatched and could undercount the owner's
        # borrower count — skip it.
        if entry.get("acked", True):
            self._release_borrow(object_id, entry["owner"])
        # Drop the borrower-side cached value too: it may hold nested
        # ObjectRef instances whose releases cascade — without eviction
        # the cache would pin every nested borrow forever (the owner-side
        # analog lives in _free_object).
        self._evict_cached(object_id)

    def _evict_cached(self, object_id: bytes) -> None:
        """Delete a memory-store entry from any thread (the store is
        loop-affine)."""
        if self.loop is None or self._shutdown.is_set():
            return
        self._post_to_loop(lambda: self.memory.delete(object_id))

    def _note_deserialized_own_ref(self, object_id: bytes) -> None:
        """A deserialized copy of one of our own refs counts as a local
        reference (its __del__ will decrement)."""
        with self._ref_lock:
            rec = self.owned.get(object_id)
            if rec is not None:
                rec.local_refs += 1

    def _free_object(self, object_id: bytes, rec: OwnedObject) -> None:
        # Inline pop (== memledger.note_free): this runs once per freed
        # object on the release hot path.
        memledger._meta.pop(object_id, None)
        with self._ref_lock:
            self.owned.pop(object_id, None)
            contained, rec.contained = rec.contained, []
        # Refs nested in this object's value lose their container pin.
        for oid, owner in contained:
            self._release_borrow(oid, owner)
        locations = list(rec.locations)
        loop = self.loop
        if loop is None or self._shutdown.is_set():
            return

        def _cleanup():
            self.memory.delete(object_id)
            for addr in locations:
                loop.create_task(self._delete_remote(addr, object_id))
        self._post_to_loop(_cleanup)

    async def _delete_remote(self, addr: str, object_id: bytes) -> None:
        try:
            await self.clients.get(addr).notify(
                "store_delete", {"object_id": object_id.hex()})
        except Exception:  # noqa: BLE001
            pass

    def _announce_location(self, ref: ObjectRef) -> None:
        """A cross-node pull just cached a REPLICA of `ref` in this
        node's store.  The owner's location directory must learn about
        it, or _free_object will only scrub the owner-side copy and the
        replica leaks forever (pre-round-10: every cross-node get of a
        since-freed object stranded its replica — the DCN collectives
        hammer exactly this pattern, one replica per ring hop)."""
        owner = ref.owner_addr
        oid = ref.binary()
        if not owner or owner == self.address:
            with self._ref_lock:
                rec = self.owned.get(oid)
                if rec is not None and self.agent_addr not in rec.locations:
                    rec.locations.append(self.agent_addr)
            return

        async def _notify():
            try:
                await self.clients.get(owner).notify(
                    "add_location",
                    {"object_id": oid.hex(), "addr": self.agent_addr})
            except Exception:  # noqa: BLE001 - owner death handled by gets
                pass
        self.loop.create_task(_notify())

    async def rpc_add_location(self, h: dict, _b: list) -> dict:
        """Owner side of _announce_location.  If the object was already
        freed while the replica was being created, scrub the replica now
        — nobody else will."""
        oid = bytes.fromhex(h["object_id"])
        addr = h["addr"]
        with self._ref_lock:
            rec = self.owned.get(oid)
            if rec is not None:
                if addr not in rec.locations:
                    rec.locations.append(addr)
                return {}
        await self._delete_remote(addr, oid)
        return {}

    async def rpc_add_borrow(self, h: dict, _b: list) -> dict:
        oid = bytes.fromhex(h["object_id"])
        self._add_borrow(oid, self.address)
        # Piggyback the location directory on the ack: the borrower is
        # about to get() this ref, and answering here collapses its
        # resolve_object round trip into the borrow registration it
        # already pays (round 10: per-chunk resolve RTs against busy
        # owners dominated ring-collective pull latency).
        rec = self.owned.get(oid)
        if rec is not None and rec.state == "stored" and rec.locations:
            return {"state": "stored", "locations": list(rec.locations)}
        return {}

    async def rpc_remove_borrow(self, h: dict, _b: list) -> dict:
        self._release_borrow(bytes.fromhex(h["object_id"]), self.address)
        return {}

    # ------------------------------------------------- owner-side resolution
    async def rpc_resolve_object(self, h: dict, _b: list) -> tuple[dict, list]:
        """Serve an object's value/locations to a borrower
        (ray: OwnershipBasedObjectDirectory asking the owner)."""
        oid = bytes.fromhex(h["object_id"])
        rec = self.owned.get(oid)
        if rec is None:
            return {"state": "unknown"}, []
        if rec.state == "pending" and h.get("wait"):
            e = self.memory.entry(oid)
            await e.event.wait()
            rec = self.owned.get(oid) or rec
        if rec.state == "inline":
            return {"state": "inline"}, list(rec.frames or [])
        if rec.state == "stored":
            return {"state": "stored", "locations": rec.locations}, []
        if rec.state == "error":
            import pickle
            return {"state": "error"}, [pickle.dumps(rec.error)]
        return {"state": "pending"}, []

    # ------------------------------------------------------------ execution
    async def rpc_push_task_batch(self, h: dict,
                                  blobs: list) -> tuple[dict, list]:
        """Batched push: execute each task in order, one combined reply
        (amortizes per-message RPC overhead on the task hot path).  One
        member's escaping exception must NOT void its completed siblings
        (their side effects and pin ACKs are already real), so every
        member is error-isolated into its own reply."""
        tasks = h["tasks"]
        fns = []
        for th in tasks:
            fn = self._task_is_simple(th)
            if fn is None:
                fns = None
                break
            fns.append(fn)
        if fns is not None:
            # Fast path: the whole batch runs in ONE executor hop
            # (deserialize → call → serialize in the thread) instead of
            # 3 thread-pool round-trips per task — the per-task context
            # switches are the dominant control-plane cost.
            return await self._push_batch_fast(tasks, blobs, fns)
        replies, out_blobs = [], []
        offset = 0
        for th in tasks:
            n = th.pop("nframes")
            try:
                reply, rb = await self.rpc_push_task(
                    th, blobs[offset:offset + n])
            except BaseException as e:  # noqa: BLE001
                reply, rb = self._error_reply(e)
            offset += n
            reply["nblobs"] = len(rb)
            replies.append(reply)
            out_blobs.extend(rb)
        return {"replies": replies}, out_blobs

    def _task_is_simple(self, th: dict):
        """The one eligibility predicate for the one-executor-hop fast
        path (single pushes AND batches): returns the cached function, or
        None when the task needs the general path (ref args, runtime_env,
        dynamic/streaming returns, cancellation, uncached function)."""
        fn = self.functions.get(th.get("function_id", ""))
        if (fn is None or th.get("arg_refs") or th.get("runtime_env")
                or th.get("dynamic") or th.get("streaming")
                or bytes.fromhex(th["task_id"]) in self._cancelled):
            return None
        return fn

    def _exec_simple_thread(self, th: dict, frames: list, fn) -> dict:
        """Executor-thread body of the fast path: deserialize args, run the
        user function, serialize returns, attempt arena store of large
        returns.  Touches no loop-affine state (memory store, asyncio)."""
        import pickle as _pickle

        rec = {"arg_contained": (), "svs": None, "err": None, "stored": ()}
        hops = th.get("_hops")
        t_span0 = time.time() if spans.ENABLED else 0.0
        if isinstance(hops, dict):
            hops["exec_start"] = time.monotonic()
        prev = self.current_task_id
        prev_trace = self.current_trace
        prev_driver = self.current_driver_addr
        prev_bundle = self.current_bundle_key
        prev_res = self.current_resources
        prev_renv = self.current_runtime_env
        self.current_task_id = th["task_id"]
        self.current_trace = th.get("trace")
        self.current_driver_addr = th.get("driver_addr") or prev_driver
        self.current_bundle_key = th.get("bundle_key")
        self.current_resources = th.get("resources")
        self.current_runtime_env = th.get("runtime_env")
        self._record_event(th["task_id"], "RUNNING", th.get("name", ""))
        try:
            value, contained = deserialize_with_refs(frames)
            rec["arg_contained"] = contained
            args, kwargs = value
            result = fn(*args, **kwargs)
            num_returns = th.get("num_returns", 1)
            values = [result] if num_returns == 1 else list(result)
            if num_returns != 1 and len(values) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but "
                    f"returned {len(values)} values")
            svs = [serialize(v) for v in values]
            rec["svs"] = svs
            stored = [None] * len(svs)
            tid = TaskID(bytes.fromhex(th["task_id"]))
            inline_max = self.config.max_inline_object_size
            for i, sv in enumerate(svs):
                if sv.total_bytes > inline_max:
                    rid = ObjectID.for_return(tid, i).binary()
                    stored[i] = self._store_frames_local(rid, sv.frames)
            rec["stored"] = stored
        except BaseException as e:  # noqa: BLE001
            tb_str = traceback.format_exc()
            try:
                payload = _pickle.dumps(e)
            except Exception:  # noqa: BLE001
                payload = _pickle.dumps(RuntimeError(str(e)))
            rec["err"] = (payload, tb_str)
        finally:
            self.current_task_id = prev
            self.current_trace = prev_trace
            self.current_driver_addr = prev_driver
            self.current_bundle_key = prev_bundle
            self.current_resources = prev_res
            self.current_runtime_env = prev_renv
            if isinstance(hops, dict):
                hops["exec_end"] = time.monotonic()
            if spans.ENABLED and t_span0:
                spans.emit_task(
                    th.get("trace"),
                    f"actor.{th['method']}" if th.get("method")
                    else f"task.{th.get('name') or 'fn'}",
                    t_span0, err="error" if rec["err"] else None)
        return rec

    async def _finalize_simple(self, th: dict, rec: dict) -> tuple[dict, list]:
        """Loop-side completion of one fast-path execution: borrow
        registration, contained-ref pins, local return caching, agent
        store fallback."""
        import pickle as _pickle

        tid = TaskID(bytes.fromhex(th["task_id"]))
        if rec["arg_contained"]:
            await self._register_borrows(rec["arg_contained"])
        if rec["err"] is not None:
            payload, tb_str = rec["err"]
            if self.mode == "worker":
                try:
                    cause = _pickle.loads(payload)
                except Exception:  # noqa: BLE001
                    cause = RuntimeError("task failed")
                err = TaskError(cause, tb_str)
                for i in range(th.get("num_returns", 1)):
                    self._cache_local_return(
                        ObjectID.for_return(tid, i).binary(), error=err)
            return {"status": "error", "traceback": tb_str}, [payload]
        returns, rb = [], []
        for i, sv in enumerate(rec["svs"]):
            contained = await self._pin_contained_refs(sv)
            rid = ObjectID.for_return(tid, i).binary()
            if rec["stored"][i] is None:       # inline-sized
                returns.append({"inline": True, "nframes": len(sv.frames),
                                "size": sv.total_bytes,
                                "contained": contained})
                rb.extend(sv.frames)
                if self.mode == "worker":
                    self._cache_local_return(rid, frames=sv.frames)
            else:
                if rec["stored"][i] is False:  # arena full/absent
                    await self.clients.get(self.agent_addr).call(
                        "store_put", {"object_id": rid.hex()}, sv.frames)
                returns.append({"inline": False,
                                "location": self.agent_addr,
                                "size": sv.total_bytes,
                                "contained": contained})
                if self.mode == "worker":
                    self._cache_local_return(rid,
                                             locations=[self.agent_addr])
        return {"status": "ok", "returns": returns}, rb

    async def _push_batch_fast(self, tasks: list, blobs: list,
                               fns: list) -> tuple[dict, list]:
        """One-executor-hop execution of a batch of simple tasks (function
        cached, no top-level ref args, no runtime_env, not dynamic).  The
        thread does the pure-Python work (deserialize, user code,
        serialize, arena store attempt); everything loop-affine (borrow
        registration, contained-ref pins, memory-store caching, agent
        RPC fallback) happens here afterwards."""
        def _run_all():
            recs = []
            offset = 0
            for th, fn in zip(tasks, fns):
                n = th["nframes"]
                recs.append(self._exec_simple_thread(
                    th, blobs[offset:offset + n], fn))
                offset += n
            return recs

        recs = await self.loop.run_in_executor(self._default_executor,
                                               _run_all)
        replies, out_blobs = [], []
        for th, rec in zip(tasks, recs):
            # Per-member isolation: a finalize failure (e.g. agent store
            # RPC down) must not void siblings whose side effects are real.
            try:
                reply, rb = await self._finalize_simple(th, rec)
            except BaseException as e:  # noqa: BLE001
                reply, rb = self._error_reply(e)
            reply["nblobs"] = len(rb)
            replies.append(reply)
            out_blobs.extend(rb)
        return {"replies": replies}, out_blobs

    async def rpc_push_task(self, h: dict, blobs: list) -> tuple[dict, list]:
        fast = False
        try:
            fn = self._task_is_simple(h)
            if fn is not None:
                # Simple single task: same one-executor-hop fast path the
                # batches use (3 thread round-trips per call otherwise —
                # the sync-call latency cost).
                fast = True
                rec = await self.loop.run_in_executor(
                    self._default_executor, self._exec_simple_thread,
                    h, blobs, fn)
                reply, rb = await self._finalize_simple(h, rec)
            else:
                reply, rb = await self._execute_pushed_task(h, blobs)
        except BaseException as e:  # noqa: BLE001
            reply, rb = self._error_reply(e)
            fast = False
        if reply.get("status") == "error" and self.mode == "worker" \
                and not fast:
            # Cache the error locally (the fast path's _finalize_simple
            # already did — don't double-fill the bounded return cache):
            # a same-batch consumer of this task's return must resolve it
            # WITHOUT an owner round-trip — the owner only learns the
            # error when the whole batch replies, which waits on that
            # consumer (deadlock otherwise).
            import pickle

            try:
                cause = pickle.loads(rb[0]) if rb else None
            except Exception:  # noqa: BLE001
                cause = None
            err = TaskError(cause or RuntimeError("task failed"),
                            reply.get("traceback", ""))
            tid = TaskID(bytes.fromhex(h["task_id"]))
            for i in range(h.get("num_returns", 1)):
                self._cache_local_return(
                    ObjectID.for_return(tid, i).binary(), error=err)
        return reply, rb

    async def _execute_pushed_task(self, h: dict,
                                   blobs: list) -> tuple[dict, list]:
        task_id = bytes.fromhex(h["task_id"])
        if task_id in self._cancelled:
            self._cancelled.discard(task_id)
            return {"status": "cancelled"}, []
        fn = await self._fetch_function(h["function_id"])
        args, kwargs = await self._resolve_args(h, blobs)
        self._record_event(h["task_id"], "RUNNING", h.get("name", ""),
                           trace=h.get("trace"))

        def _thunk():
            from ray_tpu._private import runtime_env as renv

            with renv.activate(h.get("runtime_env"), self):
                return fn(*args, **kwargs)
        if h.get("streaming"):
            try:
                return await self._run_streaming(h, _thunk,
                                                 self._default_executor)
            finally:
                self._evict_untracked_args(h)
        t_span0 = time.time() if spans.ENABLED else 0.0
        try:
            result = await self._run_user_code(
                _thunk, task_id=task_id, trace=h.get("trace"),
                driver_addr=h.get("driver_addr"),
                bundle_key=h.get("bundle_key"),
                resources=h.get("resources"),
                runtime_env=h.get("runtime_env"))
        except BaseException as e:  # noqa: BLE001
            if spans.ENABLED and t_span0:
                spans.emit_task(h.get("trace"),
                                f"task.{h.get('name') or 'fn'}",
                                t_span0, err=type(e).__name__)
            return self._error_reply(e)
        finally:
            self._evict_untracked_args(h)
        if spans.ENABLED and t_span0:
            spans.emit_task(h.get("trace"),
                            f"task.{h.get('name') or 'fn'}", t_span0)
        return await self._pack_returns(result, h)

    def _make_stream_shipper(self, h: dict):
        """Shared item shipper for streaming generators: serializes one
        item and delivers it to the owner as an ACKED stream_item call
        (the ack is the backpressure, and it guarantees every item is
        registered owner-side before the final reply — which travels on a
        different socket — can arrive)."""
        owner = h["owner_addr"]
        tid = TaskID(bytes.fromhex(h["task_id"]))
        inline_max = self.config.max_inline_object_size

        async def _ship(item, idx: int) -> None:
            sv = serialize(item)
            contained = await self._pin_contained_refs(sv)
            iid = ObjectID.for_return(tid, idx + 1).binary()
            hdr = {"task_id": h["task_id"], "index": idx,
                   "size": sv.total_bytes, "contained": contained}
            if sv.total_bytes <= inline_max:
                hdr["inline"] = True
                if self.mode == "worker":
                    self._cache_local_return(iid, frames=sv.frames)
                await self.clients.get(owner).call(
                    "stream_item", hdr, sv.frames, timeout=60.0)
            else:
                if not self._store_frames_local(iid, sv.frames):
                    await self.clients.get(self.agent_addr).call(
                        "store_put", {"object_id": iid.hex()}, sv.frames)
                hdr["inline"] = False
                hdr["location"] = self.agent_addr
                if self.mode == "worker":
                    self._cache_local_return(iid,
                                             locations=[self.agent_addr])
                await self.clients.get(owner).call("stream_item", hdr,
                                                   timeout=60.0)

        return _ship

    async def _run_streaming(self, h: dict, thunk,
                             executor) -> tuple[dict, list]:
        """Executor side of a streaming generator: iterate the user
        generator on the executor thread, shipping each item as produced
        (see _make_stream_shipper)."""
        loop = self.loop
        ship = self._make_stream_shipper(h)
        count = 0

        def _producer():
            nonlocal count
            prev = self.current_task_id
            prev_trace = self.current_trace
            prev_driver = self.current_driver_addr
            prev_bundle = self.current_bundle_key
            self.current_task_id = h["task_id"]
            self.current_trace = h.get("trace")
            self.current_driver_addr = h.get("driver_addr") or prev_driver
            self.current_bundle_key = h.get("bundle_key")
            try:
                for item in thunk():
                    asyncio.run_coroutine_threadsafe(
                        ship(item, count), loop).result()
                    count += 1
            finally:
                self.current_task_id = prev
                self.current_trace = prev_trace
                self.current_driver_addr = prev_driver
                self.current_bundle_key = prev_bundle

        try:
            await loop.run_in_executor(executor, _producer)
        except BaseException as e:  # noqa: BLE001
            reply, rb = self._error_reply(e)
            reply["streaming"] = True
            reply["streamed"] = count
            return reply, rb
        finally:
            self._evict_untracked_args(h)
        return {"status": "ok", "streaming": True, "streamed": count}, []

    async def _run_streaming_async(self, h: dict, factory,
                                   sem=None) -> tuple[dict, list]:
        """Async-actor streaming: factory() returns an async generator
        (iterated on the loop, items ship as yielded) or a coroutine
        (awaited; its value streams as a single item).  `sem` (the
        concurrency-group bound) is held across the whole stream."""
        import inspect as _inspect

        ship = self._make_stream_shipper(h)
        count = 0
        # Carry the request's trace context across the stream (same
        # reason as the async actor path: no process-global to lean on).
        token = spans.adopt_task_trace(h.get("trace"))
        try:
            if sem is not None:
                await sem.acquire()
            try:
                target = factory()
                if _inspect.isasyncgen(target):
                    async for item in target:
                        await ship(item, count)
                        count += 1
                else:
                    item = await target
                    await ship(item, count)
                    count += 1
            finally:
                if sem is not None:
                    sem.release()
        except BaseException as e:  # noqa: BLE001
            reply, rb = self._error_reply(e)
            reply["streaming"] = True
            reply["streamed"] = count
            return reply, rb
        finally:
            if token is not None:
                spans._ctx.reset(token)
            self._evict_untracked_args(h)
        return {"status": "ok", "streaming": True, "streamed": count}, []

    def _evict_untracked_args(self, h: dict) -> None:
        """Drop cached values fetched for this task's top-level ref args.
        Untracked fetches (no owned record, no borrow entry) have no
        release path of their own; left in the cache they'd pin any refs
        nested inside those values forever."""
        for r in h.get("arg_refs", ()):
            oid = bytes.fromhex(r["id"])
            if oid not in self.owned and oid not in self.borrows:
                self.memory.delete(oid)

    async def _resolve_args(self, h: dict, blobs: list) -> tuple[tuple, dict]:
        """Deserialize args (registering borrows for nested refs — ray:
        borrower protocol, reference_count.cc) and resolve top-level refs
        to values."""
        args_t, kwargs = await self._deserialize_registering(blobs)
        args = list(args_t)
        if h.get("arg_refs"):
            ref_objs = [_UntrackedRef(bytes.fromhex(r["id"]), r["owner"])
                        for r in h["arg_refs"]]
            values = await self._get_objects_async(ref_objs, None)
            for r, v in zip(h["arg_refs"], values):
                args[r["pos"]] = v
        return tuple(args), kwargs

    async def _run_user_code(self, thunk, task_id: bytes | None = None,
                             executor=None, instance_actor: str | None = None,
                             trace: dict | None = None,
                             driver_addr: str | None = None,
                             bundle_key: str | None = None,
                             resources: dict | None = None,
                             runtime_env: dict | None = None):
        prev_task = self.current_task_id
        prev_trace = self.current_trace
        prev_driver = self.current_driver_addr
        prev_bundle = self.current_bundle_key
        prev_res = self.current_resources
        prev_renv = self.current_runtime_env
        self.current_task_id = task_id.hex() if task_id else None
        self.current_trace = trace
        self.current_driver_addr = driver_addr or prev_driver
        self.current_bundle_key = bundle_key
        self.current_resources = resources
        self.current_runtime_env = runtime_env
        try:
            return await self.loop.run_in_executor(
                executor or self._default_executor, thunk)
        finally:
            self.current_task_id = prev_task
            self.current_trace = prev_trace
            self.current_bundle_key = prev_bundle
            self.current_driver_addr = prev_driver
            self.current_resources = prev_res
            self.current_runtime_env = prev_renv

    def _evicted_reply(self, seq: int) -> tuple[dict, list]:
        """Reply for a resend whose original execution completed but
        whose (large) result was trimmed from the dedupe cache: an
        explicit error, NOT a re-execution — the method's side effects
        are already applied and must not double-apply (at-most-once)."""
        from ray_tpu.exceptions import ReplyEvictedError

        return self._error_reply(ReplyEvictedError(
            f"seq {seq}: the call already executed, but its reply "
            f"(>64KiB) was evicted from the reply cache before the "
            f"resend arrived; refusing to re-execute (side effects are "
            f"applied exactly once — re-fetch state with another call)"))

    def _error_reply(self, e: BaseException) -> tuple[dict, list]:
        import pickle
        tb = traceback.format_exc()
        try:
            payload = pickle.dumps(e)
        except Exception:  # noqa: BLE001
            payload = pickle.dumps(RuntimeError(str(e)))
        return {"status": "error", "traceback": tb}, [payload]

    async def _pack_returns(self, result: Any, h: dict) -> tuple[dict, list]:
        if h.get("dynamic"):
            return await self._pack_dynamic_returns(result, h)
        num_returns = h.get("num_returns", 1)
        if num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != num_returns:
                return self._error_reply(ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(values)} values"))
        returns, out_blobs = [], []
        task_id = bytes.fromhex(h["task_id"])
        for i, v in enumerate(values):
            sv = await self.loop.run_in_executor(None, serialize, v)
            contained = await self._pin_contained_refs(sv)
            rid = ObjectID.for_return(TaskID(task_id), i).binary()
            if sv.total_bytes <= self.config.max_inline_object_size:
                returns.append({"inline": True, "nframes": len(sv.frames),
                                "size": sv.total_bytes,
                                "contained": contained})
                out_blobs.extend(sv.frames)
                if self.mode == "worker":
                    self._cache_local_return(rid, frames=sv.frames)
            else:
                stored = await self.loop.run_in_executor(
                    None, self._store_frames_local, rid, sv.frames)
                if not stored:
                    reply, _ = await self.clients.get(self.agent_addr).call(
                        "store_put", {"object_id": rid.hex()}, sv.frames)
                returns.append({"inline": False,
                                "location": self.agent_addr,
                                "size": sv.total_bytes,
                                "contained": contained})
                if self.mode == "worker":
                    self._cache_local_return(
                        rid, locations=[self.agent_addr])
        return {"status": "ok", "returns": returns}, out_blobs

    async def _pin_contained_refs(self, sv) -> list:
        """Pin refs nested in a return value — added HERE and ACKED
        before the reply, because the reply releases the caller's
        submission pins (different connection: no FIFO guarantee) — the
        pins become owned by the caller's return-object record, which
        releases them when the return object is freed (ray:
        contained-in-owned refs, reference_count.cc).  Only pins that
        actually landed are reported: the caller's later release must
        match an add, or the owner undercounts."""
        pairs = self._dedup_contained(sv.contained_refs)
        pinned: list[tuple[bytes, str]] = []
        remote_pins = []
        for oid, owner in pairs:
            if owner == self.address:
                with self._ref_lock:
                    rec_c = self.owned.get(oid)
                    if rec_c:
                        rec_c.borrowers += 1
                        pinned.append((oid, owner))
            else:
                remote_pins.append((oid, owner))
        if remote_pins:
            pinned.extend(await self._pin_remote(remote_pins))
        return [[oid.hex(), owner] for oid, owner in pinned]

    def _cache_local_return(self, rid: bytes, frames: list | None = None,
                            locations: list | None = None,
                            error: BaseException | None = None) -> None:
        """Locality cache: a same-worker consumer resolves this return
        without an owner round-trip — which would DEADLOCK inside a
        batched push (the producer's reply ships only when the whole
        batch completes) and is a wasted RTT otherwise.  Retried tasks
        overwrite by object id; as in the reference, retries assume
        deterministic tasks (a stale copy on a worker equals a stale
        plasma copy on a node)."""
        e = self.memory.entry(rid)
        # Reset before set: a retried task that failed here earlier must
        # not leave its stale error (or stale frames) shadowing the new
        # outcome for same-worker consumers.
        e.frames, e.locations, e.error, e.has_value, e.value = \
            None, [], None, False, None
        if frames is not None:
            e.frames = frames
        if locations is not None:
            e.locations = list(locations)
        if error is not None:
            e.error = error
        e.wake()
        self._return_cache.append(rid)
        while len(self._return_cache) > 512:
            old = self._return_cache.pop(0)
            if old not in self.owned and old not in self.borrows:
                self.memory.delete(old)

    async def _pack_dynamic_returns(self, result: Any,
                                    h: dict) -> tuple[dict, list]:
        """num_returns="dynamic": materialize the generator's items as
        individual return objects (item i → return index i+1; index 0 is
        the generator descriptor the caller resolves to an
        ObjectRefGenerator).  ray: dynamic generator returns."""
        task_id = bytes.fromhex(h["task_id"])
        try:
            iter(result)
        except TypeError:
            return self._error_reply(TypeError(
                'num_returns="dynamic" requires the task to return an '
                f"iterable/generator, got {type(result).__name__}"))
        # The generator BODY runs lazily — drain it in the executor like
        # any other user code (on the loop it would stall all RPC
        # handling); body exceptions propagate via the generic error path.
        items = await self.loop.run_in_executor(None, list, result)
        metas, out_blobs = [], []
        for i, v in enumerate(items):
            sv = await self.loop.run_in_executor(None, serialize, v)
            contained = await self._pin_contained_refs(sv)
            rid = ObjectID.for_return(TaskID(task_id), i + 1).binary()
            if sv.total_bytes <= self.config.max_inline_object_size:
                metas.append({"inline": True, "nframes": len(sv.frames),
                              "size": sv.total_bytes,
                              "contained": contained})
                out_blobs.extend(sv.frames)
                if self.mode == "worker":
                    self._cache_local_return(rid, frames=sv.frames)
            else:
                stored = await self.loop.run_in_executor(
                    None, self._store_frames_local, rid, sv.frames)
                if not stored:
                    await self.clients.get(self.agent_addr).call(
                        "store_put", {"object_id": rid.hex()}, sv.frames)
                metas.append({"inline": False,
                              "location": self.agent_addr,
                              "size": sv.total_bytes,
                              "contained": contained})
                if self.mode == "worker":
                    self._cache_local_return(
                        rid, locations=[self.agent_addr])
        return {"status": "ok",
                "returns": [{"inline": True, "nframes": 0,
                             "contained": [], "dynamic": metas}]}, out_blobs

    # --------------------------------------------------------------- actors
    async def rpc_create_actor(self, h: dict, blobs: list) -> dict:
        prev_actor_id = self.current_actor_id
        try:
            cls = await self._fetch_function(h["function_id"])
            args, kwargs = await self._resolve_args(h, blobs)
            is_async = bool(h.get("is_async"))
            renv_desc = h.get("runtime_env")
            # Visible DURING __init__: an actor constructor may ask
            # get_runtime_context().get_actor_id() (ray allows it).
            self.current_actor_id = h["actor_id"]

            def _construct():
                from ray_tpu._private import runtime_env as renv

                with renv.activate(renv_desc, self):
                    return cls(*args, **kwargs)
            if is_async:
                if renv_desc and (renv_desc.get("packages")
                                  or renv_desc.get("pip")):
                    # Packages/pip envs must be on disk before activate
                    # runs on the loop thread (see runtime_env.prefetch).
                    from ray_tpu._private import runtime_env as renv

                    await self.loop.run_in_executor(
                        None, renv.prefetch, renv_desc, self)
                instance = _construct()
            else:
                instance = await self.loop.run_in_executor(
                    self._default_executor, _construct)
            self.actors_hosted[h["actor_id"]] = ActorInstance(
                h["actor_id"], instance,
                max_concurrency=h.get("max_concurrency"),
                is_async=is_async, runtime_env=renv_desc,
                concurrency_groups=h.get("concurrency_groups"),
                method_groups=h.get("method_groups"),
                bundle_key=h.get("bundle_key"))
            return {"ok": True}
        except BaseException as e:  # noqa: BLE001
            self.current_actor_id = prev_actor_id
            return {"error": f"{type(e).__name__}: {e}\n"
                             f"{traceback.format_exc()}"}
        finally:
            self._evict_untracked_args(h)

    async def rpc_actor_call(self, h: dict, blobs: list) -> tuple[dict, list]:
        inst = self.actors_hosted.get(h.get("actor_id", ""))
        if inst is not None and self._actor_batch_simple(inst, [h]):
            # Lone simple call: the same one-executor-hop treatment the
            # batch fast path gets (deserialize→run→serialize in the
            # thread) — this is the sync actor-call latency path, which
            # otherwise pays 3 thread round-trips per call.  Delegate to
            # the batch implementation (ONE copy of the seqno-advance /
            # successor-wake / execute protocol) and unwrap.
            reply, out_blobs = await self._actor_batch_fast(
                inst, [{**h, "nframes": len(blobs)}], blobs)
            single = reply["replies"][0]
            single.pop("nblobs", None)
            return single, out_blobs
        started = await self._actor_call_begin(h, blobs)
        return await started

    def _actor_batch_simple(self, inst: ActorInstance, calls: list) -> bool:
        """True when the whole batch can run as one executor thunk: sync
        single-threaded actor (executor FIFO preserves call order across
        concurrent batches), contiguous in-order seqnos from one caller,
        no ref args / runtime_env / dynamic returns."""
        if inst.is_async or inst.max_concurrency != 1 or inst.runtime_env \
                or inst.concurrency_groups:
            return False
        caller = calls[0].get("caller")
        expected = inst.next_seq.get(
            caller, calls[0].get("seq_floor", calls[0].get("seqno", 0)))
        for ch in calls:
            if (ch.get("arg_refs") or ch.get("dynamic")
                    or ch.get("streaming")
                    or ch.get("actor_id") != inst.actor_id
                    or ch.get("caller") != caller
                    or ch.get("seqno", 0) != expected
                    or not callable(getattr(inst.instance,
                                            ch.get("method", ""), None))):
                return False
            expected += 1
        return True

    async def _actor_batch_fast(self, inst: ActorInstance, calls: list,
                                blobs: list) -> tuple[dict, list]:
        """One-executor-hop execution of a simple actor-call batch (see
        _push_batch_fast).  Seqnos advance for the whole batch up front —
        the batch occupies one FIFO slot on the actor's executor, so a
        later batch's thunk queues behind it and order is preserved."""
        caller = calls[0].get("caller")
        last_seq = calls[-1].get("seqno", 0)
        inst.next_seq[caller] = last_seq + 1
        buf = inst.buffered.get(caller, {})
        nxt_fut = buf.pop(last_seq + 1, None)
        if nxt_fut and not nxt_fut.done():
            nxt_fut.set_result(None)
        # Dedupe entries BEFORE execution: a retransmit racing this batch
        # must share these replies, not re-run the methods.
        shared = {}
        for ch in calls:
            fut = self.loop.create_future()
            shared[ch.get("seqno", 0)] = fut
            inst.cache_reply((caller, ch.get("seqno", 0)), fut)

        methods = [getattr(inst.instance, ch["method"]) for ch in calls]

        def _run_all():
            recs = []
            offset = 0
            for ch, m in zip(calls, methods):
                n = ch["nframes"]
                recs.append(self._exec_simple_thread(
                    ch, blobs[offset:offset + n], m))
                offset += n
            return recs

        try:
            recs = await self.loop.run_in_executor(inst.executor, _run_all)
            replies, out_blobs = [], []
            for ch, rec in zip(calls, recs):
                try:
                    reply, rb = await self._finalize_simple(ch, rec)
                except BaseException as e:  # noqa: BLE001
                    reply, rb = self._error_reply(e)
                fut = shared.get(ch.get("seqno", 0))
                if fut is not None and not fut.done():
                    fut.set_result((dict(reply), rb))  # pre-"nblobs" copy
                reply["nblobs"] = len(rb)
                replies.append(reply)
                out_blobs.extend(rb)
            return {"replies": replies}, out_blobs
        except BaseException as e:
            # Never leave a dedupe future pending: a resend awaiting it
            # would hang forever.
            for fut in shared.values():
                if not fut.done():
                    fut.set_result(self._error_reply(e))
            raise

    async def rpc_actor_call_batch(self, h: dict,
                                   blobs: list) -> tuple[dict, list]:
        """Batched actor calls from one caller: START all in seqno order
        (so async/threaded actors still overlap execution), then gather
        the replies into one message (amortizes per-call RPC overhead)."""
        calls = h["calls"]
        if calls:
            inst = self.actors_hosted.get(calls[0].get("actor_id", ""))
            if inst is not None and self._actor_batch_simple(inst, calls):
                return await self._actor_batch_fast(inst, calls, blobs)
        finishers = []
        offset = 0
        for ch in h["calls"]:
            n = ch.pop("nframes")
            finishers.append(
                await self._actor_call_begin(ch, blobs[offset:offset + n]))
            offset += n
        # Error-isolate each member: a sibling's escaping exception must
        # not abort calls that already executed (their side effects are
        # real; a batch-level error would retry or fail them all).
        results = await asyncio.gather(*finishers,
                                       return_exceptions=True)
        replies, out_blobs = [], []
        for r in results:
            if isinstance(r, BaseException):
                rh, rb = self._error_reply(r)
            else:
                rh, rb = r
            rh["nblobs"] = len(rb)
            replies.append(rh)
            out_blobs.extend(rb)
        return {"replies": replies}, out_blobs

    async def _actor_call_begin(self, h: dict, blobs: list):
        """Ordering + dispatch phase; returns an awaitable yielding the
        packed reply (execution proceeds concurrently after dispatch)."""
        inst = self.actors_hosted.get(h["actor_id"])
        if inst is None:
            async def _not_hosted():
                return ({"status": "error",
                         "traceback": "actor not hosted here"},
                        [__import__("pickle").dumps(
                            ActorDiedError(h["actor_id"], "not hosted"))])
            return _not_hosted()
        caller = h.get("caller", "?")
        seq = h.get("seqno", 0)
        if os.environ.get("RAY_TPU_ACTOR_TRACE"):
            logger.info("actor_call %s seq=%s nxt=%s method=%s",
                        h["actor_id"][:12], seq,
                        inst.next_seq.get(caller), h.get("method"))
        # The caller's seq_floor (lowest unacked seqno at send time) is
        # the baseline for a first-contact caller — NOT this call's own
        # seqno: a reordered first batch would otherwise set the baseline
        # past its preceding calls, demoting them to "stale retries"
        # executed out of order.  A restarted actor incarnation still
        # accepts the caller's continuing sequence (floor > 0 after acks).
        floor = h.get("seq_floor")
        nxt = inst.next_seq.setdefault(
            caller, seq if floor is None else floor)
        if floor is not None and floor > nxt:
            # Seqnos [nxt, floor) were acked or terminally failed
            # submitter-side and will never arrive; without this advance
            # every later call parks forever behind the gap.  Wake EVERY
            # parked call at or below the floor, not just buffered[floor]:
            # a call delivered before its predecessors terminally failed
            # would otherwise wait on a future nobody resolves (leaking
            # its dispatch task and arg blobs).  Woken stale entries
            # (seq < floor) re-check on resume and take the reply-cache /
            # at-least-once path.
            inst.next_seq[caller] = nxt = floor
            buf = inst.buffered.get(caller, {})
            for s in sorted(s for s in buf if s <= floor):
                gap_fut = buf.pop(s)
                if gap_fut and not gap_fut.done():
                    gap_fut.set_result(None)
        if seq < nxt:
            # Stale seqno: a retry resend after connection loss (the reply
            # was lost, OR the retry raced an execution still in flight).
            # Share the ORIGINAL execution's reply — re-running would
            # double-apply stateful methods (a counter once advanced by a
            # retransmitted batch whose originals were mid-execution).
            hit = inst.reply_cache.get((caller, seq))
            if hit is REPLY_EVICTED:
                return self._immediate_reply(self._evicted_reply(seq))
            if hit is not None:
                return self._share_reply(hit)
            # Beyond the dedupe window: execute out of order — the
            # documented at-least-once fallback, never park (a parked
            # stale seq would never be woken: completions only pop
            # upward).
            try:
                started = await self._start_actor_method(inst, h, blobs)
            except BaseException as e:  # noqa: BLE001
                return self._immediate_reply(self._error_reply(e))
            return started
        if seq != nxt:
            # Out-of-order arrival: park until predecessors START
            # (ray: ActorSchedulingQueue buffering by seq_no).  A resend
            # of an already-parked seqno must JOIN the original's park
            # future, not replace it — the clobbered original would wait
            # forever on a future nobody resolves.
            fut = inst.buffered.setdefault(caller, {}).setdefault(
                seq, self.loop.create_future())
            await fut
            # A seq_floor fast-forward may have woken us STALE (our
            # predecessors terminally failed and the floor moved past
            # us): serve the original reply if cached, else execute out
            # of order (at-least-once fallback) WITHOUT touching
            # next_seq — the in-order epilogue below would rewind it
            # past the floor and re-demote every later call.
            if seq < inst.next_seq.get(caller, 0):
                hit = inst.reply_cache.get((caller, seq))
                if hit is REPLY_EVICTED:
                    return self._immediate_reply(self._evicted_reply(seq))
                if hit is not None:
                    return self._share_reply(hit)
                try:
                    started = await self._start_actor_method(inst, h,
                                                             blobs)
                except BaseException as e:  # noqa: BLE001
                    return self._immediate_reply(self._error_reply(e))
                return started
        # In-order start, possibly-concurrent execution: async actors and
        # threaded actors (max_concurrency > 1) overlap; the default
        # single-thread executor serializes (ray: fiber.h vs ordered queue).
        # The sequence MUST advance even when dispatch fails (bad args, arg
        # resolution error): a burned seqno would otherwise park every later
        # call from this caller forever.
        hit = inst.reply_cache.get((caller, seq))
        if hit is not None and hit is not REPLY_EVICTED:
            # A resend racing the ORIGINAL's still-running dispatch: arg
            # resolution (a slow pull, lineage) can outlast the reply
            # watchdog, and next_seq only advances after dispatch — so
            # dedupe on the reply-cache placeholder the original
            # registered below, never re-execute.
            return self._share_reply(hit)
        # The placeholder goes in BEFORE the first await (loop-atomic
        # with the check above); next_seq still advances only after
        # dispatch, so executor submission order keeps matching seqno
        # order (advancing early would let the successor submit first).
        shared: asyncio.Future = self.loop.create_future()
        inst.cache_reply((caller, seq), shared)
        try:
            started = await self._start_actor_method(inst, h, blobs)
        except BaseException as e:  # noqa: BLE001
            if not shared.done():
                shared.set_result(self._error_reply(e))
            return self._share_reply(shared)
        finally:
            inst.next_seq[caller] = seq + 1
            buf = inst.buffered.get(caller, {})
            nxt_fut = buf.pop(seq + 1, None)
            if nxt_fut and not nxt_fut.done():
                nxt_fut.set_result(None)
        self.loop.create_task(self._pipe_reply(started, shared))
        return self._share_reply(shared)

    async def _pipe_reply(self, started, shared: "asyncio.Future") -> None:
        """Resolve a pre-registered dedupe future from an execution's
        awaitable (never leave it pending — resends await it)."""
        try:
            res = await started
        except BaseException as e:  # noqa: BLE001
            res = self._error_reply(e)
        if not shared.done():
            shared.set_result(res)

    @staticmethod
    def _share_reply(fut):
        """Awaitable over a SHARED reply future: shielded, so one
        consumer's cancellation (connection close mid-reply) cannot kill
        the execution other resends share."""
        async def _get():
            return await asyncio.shield(fut)
        return _get()

    @staticmethod
    def _immediate_reply(reply: tuple):
        async def _done():
            return reply
        return _done()

    async def _start_actor_method(self, inst: ActorInstance, h: dict,
                                  blobs: list):
        """Resolve args and dispatch the method; returns an awaitable that
        yields the packed reply.  Dispatch (executor submit / task create)
        happens before returning, so callers can release the sequence lock
        while execution proceeds."""
        if h["method"] == "__ray_call__":
            # Generic run-this-callable-on-the-actor dispatch (ray:
            # ActorHandle._actor_method_call's __ray_call__): the first
            # arg is a function receiving the instance.  Library layers
            # (e.g. compiled-DAG execution loops) build on this without
            # core knowing about them.
            def method(fn, *a, _inst=inst.instance, **kw):  # noqa: ANN001
                return fn(_inst, *a, **kw)
        else:
            method = getattr(inst.instance, h["method"], None)
        if method is None:
            async def _err():
                return self._error_reply(
                    AttributeError(f"actor has no method {h['method']!r}"))
            return _err()
        args, kwargs = await self._resolve_args(h, blobs)
        task_id = bytes.fromhex(h["task_id"])
        self._record_event(h["task_id"], "RUNNING",
                           f"{type(inst.instance).__name__}.{h['method']}",
                           trace=h.get("trace"))
        group = inst.group_of(h)   # named concurrency group (or None)
        if h.get("streaming"):
            import inspect as _inspect

            if _inspect.isasyncgenfunction(method) or (
                    inst.is_async
                    and asyncio.iscoroutinefunction(method)):
                # Async generator (or coroutine) method: iterate on the
                # loop, shipping items as yielded; the group's semaphore
                # is held for the stream's duration.
                sem = inst.semaphore_for(group) if group \
                    else inst.default_semaphore()
                return self._run_streaming_async(
                    h, lambda: method(*args, **kwargs), sem)

            # Sync streaming generator method: items ship as produced; the
            # generator runs on the actor's (group's) own executor (FIFO
            # with its other calls).
            def _gen_thunk():
                from ray_tpu._private import runtime_env as renv

                with renv.activate(inst.runtime_env, self):
                    return method(*args, **kwargs)
            return self._run_streaming(h, _gen_thunk,
                                       inst.executor_for(group))
        t_span0 = time.time() if spans.ENABLED else 0.0
        if inst.is_async and asyncio.iscoroutinefunction(method):
            # Concurrency bound: named group's semaphore, or the default
            # group's (only active once the actor declares groups).
            sem = inst.semaphore_for(group) if group \
                else inst.default_semaphore()
            if inst.runtime_env and (inst.runtime_env.get("packages")
                                     or inst.runtime_env.get("pip")):
                # Packages/pip envs must be on disk before activate runs
                # on the loop thread (see runtime_env.prefetch).
                from ray_tpu._private import runtime_env as renv

                await self.loop.run_in_executor(
                    None, renv.prefetch, inst.runtime_env, self)

            async def _run_async():
                from ray_tpu._private import runtime_env as renv

                # Async actor methods never set the process-global
                # current_trace (they interleave on one loop); the
                # handler task carries the request's trace context in
                # its own contextvars copy instead, so nested handle
                # calls / recorder spans continue THIS request's trace.
                spans.adopt_task_trace(h.get("trace"))

                async def _invoke():
                    if inst.runtime_env:
                        # env_vars/working_dir stay active across awaits;
                        # with concurrent async methods of differently-
                        # enved actors this is best-effort (same
                        # documented limitation as runtime_env.activate).
                        with renv.activate(inst.runtime_env, self):
                            return await method(*args, **kwargs)
                    return await method(*args, **kwargs)

                if sem is None:
                    return await _invoke()
                async with sem:
                    return await _invoke()

            atask = self.loop.create_task(_run_async())
            self._running_async[task_id] = atask
        else:
            def _call():
                from ray_tpu._private import runtime_env as renv

                prev = self.current_task_id
                prev_trace = self.current_trace
                prev_driver = self.current_driver_addr
                self.current_task_id = h["task_id"]
                self.current_trace = h.get("trace")
                self.current_driver_addr = (h.get("driver_addr")
                                            or prev_driver)
                try:
                    with renv.activate(inst.runtime_env, self):
                        return method(*args, **kwargs)
                finally:
                    self.current_task_id = prev
                    self.current_trace = prev_trace
                    self.current_driver_addr = prev_driver
            atask = self.loop.run_in_executor(inst.executor_for(group),
                                              _call)

        async def _finish():
            try:
                result = await atask
            except asyncio.CancelledError:
                return {"status": "cancelled"}, []
            except BaseException as e:  # noqa: BLE001
                if spans.ENABLED and t_span0:
                    spans.emit_task(h.get("trace"),
                                    f"actor.{h['method']}", t_span0,
                                    err=type(e).__name__)
                return self._error_reply(e)
            finally:
                self._running_async.pop(task_id, None)
                self._evict_untracked_args(h)
            if spans.ENABLED and t_span0:
                spans.emit_task(h.get("trace"), f"actor.{h['method']}",
                                t_span0)
            return await self._pack_returns(result, h)

        return _finish()

    async def rpc_kill_actor_local(self, h: dict, _b: list) -> dict:
        self.actors_hosted.pop(h["actor_id"], None)
        return {}

    # -------- caller side --------
    def _actor_state(self, actor_id: str) -> ActorSubmitState:
        st = self.actor_states.get(actor_id)
        if st is None:
            st = ActorSubmitState(actor_id)
            self.actor_states[actor_id] = st
        return st

    def submit_actor_task(self, actor_id: str, method: str, args: tuple,
                          kwargs: dict, options: dict) -> list[ObjectRef]:
        task_id = TaskID.from_random()
        num_returns = options.get("num_returns", 1)
        return_ids = [ObjectID.for_return(task_id, i).binary()
                      for i in range(num_returns)]
        header, blobs, borrowed = self._build_task_payload(
            task_id.binary(), "", args, kwargs, num_returns, {}, None, options)
        header.update({"actor_id": actor_id, "method": method,
                       "caller": self.worker_id})
        if options.get("concurrency_group"):
            header["concurrency_group"] = options["concurrency_group"]
        if options.get("streaming"):
            self._ret0_task_ids[return_ids[0]] = task_id.binary()
        with self._ref_lock:
            for rid in return_ids:
                rec = self.owned.setdefault(rid, OwnedObject())
                rec.local_refs += 1
        if memledger.ENABLED:
            site = "(actor) " + method
            for rid in return_ids:
                memledger.note_create(rid, "task_return", site)
        refs = [ObjectRef(rid, self.address) for rid in return_ids]
        max_task_retries = options.get("max_task_retries", 0)
        st = self._actor_state(actor_id)
        direct_cli = None
        with st.submit_lock:
            # Seqno at SUBMIT time (not loop time): submission order ==
            # seqno order no matter which path carries the call, and the
            # receiver's parking protocol handles any transport
            # interleaving between the two paths.
            header["seqno"] = st.seqno
            st.seqno += 1
            prior_unacked = st.unacked
            st.unacked += 1
            addr = st.address
            if (self._sync_fastpath and prior_unacked == 0 and addr
                    and not st.dead
                    and not st.outbox and num_returns == 1
                    and not options.get("streaming")
                    and max_task_retries == 0
                    and not borrowed and not header.get("arg_refs")
                    and addr not in self._dead_worker_addrs):
                # Sole in-flight call to a resolved live actor: eligible
                # for the fused sync fast path.  Requires an EXISTING
                # client (RpcClient construction is loop-bound).
                cli = self.clients._clients.get(addr)
                if cli is not None and not cli._closed:
                    direct_cli = cli
                    # In inflight_seqs BEFORE the lock releases: a
                    # racing loop-path submit must compute a seq_floor
                    # that includes this still-in-flight call, or the
                    # receiver would fast-forward past it and execute
                    # the two out of order.
                    st.inflight_seqs.add(header["seqno"])
        if direct_cli is not None:
            # With unacked==0 every earlier seqno is terminally settled,
            # so our own seqno is the correct floor.
            header["seq_floor"] = header["seqno"]
            if self._submit_actor_direct(st, direct_cli, header, blobs,
                                         return_ids):
                return refs
            # Fallback: leave the seqno IN inflight_seqs — the loop
            # path's _send_actor_batch re-adds it (idempotent) and its
            # finally removes it; discarding here would reopen the
            # floor window until the outbox drains.

        def _go():
            self.memory_entries_for(return_ids)
            self._push_actor_task(
                st, header, blobs, return_ids, max_task_retries, borrowed)

        self._post_to_loop(_go)
        return refs

    def _submit_actor_direct(self, st: ActorSubmitState, cli, header: dict,
                             blobs: list, return_ids: list[bytes]) -> bool:
        """Fused sync-path submit (the ISSUE-1 round-trip collapse): the
        request posts straight to the rpc IO thread and the reply wakes a
        blocked getter FROM the IO thread — the caller's critical path
        crosses no event loop in either direction.  Owner bookkeeping
        (_on_task_reply) still runs on the loop, posted off that path.
        Returns False to fall back to the loop path (nothing sent)."""
        task = PendingTask(
            task_id=bytes.fromhex(header["task_id"]), header=header,
            blobs=blobs, return_ids=return_ids, retries_left=0,
            retry_exceptions=False, scheduling_key=(), borrowed=[],
            actor_state=st)
        addr = cli.address
        try:
            cfut = cli.call_direct_start("actor_call", header, blobs)
        except Exception:  # noqa: BLE001 - client raced closed: loop path
            return False
        self.memory_entries_for(return_ids)     # thread-safe store
        rid0 = return_ids[0]
        self._sync_calls[rid0] = _SyncCall(task, cfut, cli)
        self._direct_sync_calls += 1

        def _on_reply(f):
            # Resolving thread (IO thread, or close()): keep it tiny —
            # release the unacked slot NOW so the next sync call can
            # take the fast path before the loop finalize runs, then
            # post the real bookkeeping to the loop.
            if task.actor_state is not None:
                with st.submit_lock:
                    st.unacked -= 1
                    st.inflight_seqs.discard(header.get("seqno", 0))
                task.actor_state = None
            try:
                self._post_to_loop(
                    lambda: self._finalize_direct(task, st, f, rid0, addr))
            except RuntimeError:
                pass        # shutdown: nothing left to bookkeep

        cfut.add_done_callback(_on_reply)
        resend_s = self.config.actor_reply_resend_s
        if resend_s and resend_s > 0:
            # Lost-reply watchdog for the fused path (the loop path has
            # its own in _actor_call_with_resend): periodically resend
            # the SAME msgid until the reply future resolves.  The
            # receiver dedupes by seqno, so the retry is safe; genuine
            # actor death resolves cfut via ConnectionLost (death
            # broadcast → clients.drop) and stops the timer chain.
            timer = []      # TimerHandle box, owned by the loop thread

            def _watchdog():
                timer.clear()
                if cfut.done():
                    return
                logger.warning(
                    "no reply for direct actor call seq=%s to %s after "
                    "%.1fs; resending (receiver dedupes by seqno)",
                    header.get("seqno"), addr, resend_s)
                try:
                    cli.resend_direct(cfut, "actor_call", header, blobs)
                except Exception:  # noqa: BLE001 - client closed: cfut
                    return         # already failed with ConnectionLost
                timer.append(self.loop.call_later(resend_s, _watchdog))

            def _cancel_timer(_f):
                # Cancel NOW, not at expiry: the pending timer pins the
                # call's header and arg blobs — at a sustained call rate
                # that is resend_s seconds of already-answered argument
                # buffers held live.  Handle.cancel() drops the closure
                # immediately.
                try:
                    self._post_to_loop(
                        lambda: timer and timer.pop().cancel())
                except RuntimeError:
                    pass    # shutdown: loop (and timer) already gone

            try:
                self._post_to_loop(lambda: timer.append(
                    self.loop.call_later(resend_s, _watchdog)))
            except RuntimeError:
                pass        # shutdown race: call resolves via close()
            else:
                cfut.add_done_callback(_cancel_timer)
        return True

    def _finalize_direct(self, task: PendingTask, st: ActorSubmitState,
                         cfut, rid0: bytes, addr: str) -> None:
        """Loop-side completion of a direct-path actor call: fills the
        owner record exactly like the loop path would, so every other
        resolution surface (entry events, wait(), borrowers) observes
        the same outcome."""
        self._sync_calls.pop(rid0, None)
        try:
            kind, a, b = cfut.result()
        except Exception as e:  # noqa: BLE001 - transport loss
            if st.address == addr:
                st.address = None
            self._fail_actor_call(task, ActorError(
                st.actor_id, f"actor worker connection lost: {e}"))
            return
        if kind == "ok":
            self._on_task_reply(task, a, b)
            return
        # Remote handler raised (the transport-level error reply): the
        # at-most-once discipline of the loop path applies.
        import pickle

        try:
            exc, _tb = pickle.loads(a)
        except Exception:  # noqa: BLE001 - unpicklable remote error
            exc = RemoteError("actor_call", "remote failure")
        self._fail_actor_call(
            task, ActorError(st.actor_id, f"actor call failed: {exc!r}"))

    def _finish_sync_call(self, ref: ObjectRef, sc: _SyncCall,
                          timeout: float | None):
        """User-thread wait of a fused sync actor call: block on the
        reply future directly.  Anything non-trivial (errors, multi/
        stored/ref-bearing returns, transport loss, slow replies) hands
        off to the normal resolution paths via _GET_MISS — the loop-side
        finalize fills the owner record regardless of this wait."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            wait_s = 5.0 if deadline is None else \
                min(5.0, max(0.0, deadline - time.monotonic()))
            try:
                kind, a, b = sc.cfut.result(wait_s)
                break
            except concurrent.futures.TimeoutError:
                if deadline is not None \
                        and time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        f"get() timed out waiting for {ref.hex()[:12]}")
                if deadline is None:
                    # Same discipline as _get_objects_fast: never wait
                    # unbounded on one event source — the async path has
                    # the death-event and watchdog machinery.
                    return CoreWorker._GET_MISS
            except Exception:  # noqa: BLE001 - transport loss
                return CoreWorker._GET_MISS
        if kind != "ok" or a.get("status") != "ok":
            return CoreWorker._GET_MISS      # errors flow via the record
        returns = a.get("returns") or []
        if len(returns) != 1:
            return CoreWorker._GET_MISS
        meta = returns[0]
        if (not meta.get("inline") or meta.get("dynamic") is not None
                or meta.get("contained")):
            return CoreWorker._GET_MISS
        value, contained = deserialize_with_refs(
            b[:meta.get("nframes", len(b))])
        if contained:
            return CoreWorker._GET_MISS      # borrow registration: loop
        return value

    def _push_actor_task(self, st: ActorSubmitState, header: dict,
                         blobs: list, return_ids: list[bytes],
                         retries: int,
                         borrowed: list | None = None) -> None:
        task = PendingTask(
            task_id=bytes.fromhex(header["task_id"]), header=header,
            blobs=blobs, return_ids=return_ids, retries_left=0,
            retry_exceptions=False, scheduling_key=(),
            borrowed=borrowed or [], actor_state=st)
        # Coalescing outbox: one drainer per actor sends queued calls in
        # seqno order, many per RPC when the queue is deep (per-message
        # overhead is the 1:1 actor-call throughput cost); a lone call
        # goes out immediately as a single actor_call.
        st.outbox.append((task, retries))
        if not st.draining:
            st.draining = True
            self.loop.create_task(self._drain_actor_outbox(st))

    async def _drain_actor_outbox(self, st: ActorSubmitState) -> None:
        """Dispatch outbox batches, keeping several in flight: a batch's
        reply arrives only when its calls COMPLETE, so awaiting each batch
        would serialize long-running calls on async/threaded actors.
        zmq per-connection ordering + receiver seqno parking preserve call
        order across concurrent batches."""
        if st.send_sem is None:
            st.send_sem = asyncio.Semaphore(
                self.config.actor_max_inflight_batches)
        try:
            while st.outbox:
                limit = self.config.actor_call_batch_size
                if st.outbox[0][0].header.get("streaming"):
                    # Streaming calls ride alone: their reply waits on the
                    # LAST generated item, which would gate every batch
                    # sibling's reply behind the whole stream.
                    batch = st.outbox[:1]
                else:
                    batch = []
                    for entry in st.outbox[:limit]:
                        if entry[0].header.get("streaming"):
                            break
                        batch.append(entry)
                del st.outbox[:len(batch)]
                await st.send_sem.acquire()
                t = self.loop.create_task(self._send_actor_batch(st, batch))
                t.add_done_callback(lambda _t, s=st: s.send_sem.release())
        finally:
            st.draining = False
            if st.outbox:
                st.draining = True
                self.loop.create_task(self._drain_actor_outbox(st))

    def _fail_actor_call(self, task: PendingTask,
                         err: BaseException) -> None:
        if task.actor_state is not None:
            with task.actor_state.submit_lock:
                task.actor_state.unacked -= 1
            task.actor_state = None
        for rid in task.return_ids:
            self._resolve_error(rid, err)
        self._release_task_borrows(task)

    async def _send_actor_batch(self, st: ActorSubmitState,
                                batch: list) -> None:
        """Deliver one batch (retrying per-call budgets on connection
        loss); returns once every call has a reply or a terminal error."""
        seqs = [t.header.get("seqno", 0) for t, _ in batch]
        with st.submit_lock:
            # inflight_seqs is shared with the fused direct path (adds
            # from user threads, removes from the IO thread) — every
            # multi-element mutation and the floor's min() iterate it
            # under the submit lock.
            st.inflight_seqs.update(seqs)
        try:
            await self._send_actor_batch_inner(st, batch)
        finally:
            with st.submit_lock:
                st.inflight_seqs.difference_update(seqs)

    async def _send_actor_batch_inner(self, st: ActorSubmitState,
                                      batch: list) -> None:
        while True:
            if st.dead:
                err = ActorDiedError(st.actor_id, st.death_cause)
                for task, _ in batch:
                    self._fail_actor_call(task, err)
                return
            addr = await self._resolve_actor_addr(st)
            if addr is None:
                continue    # loops back; st.dead set or address refreshed
            if addr in self._dead_worker_addrs:
                # Known-dead worker: zmq would hang on a fresh connection.
                # BUT the OS recycles ports — a stale death broadcast can
                # name the address a NEW live worker now occupies.  Probe:
                # if the current occupant hosts OUR actor, unmark and send.
                try:
                    reply, _ = await self.clients.get(addr).call(
                        "ping", {}, timeout=2.0)
                    if st.actor_id not in (reply or {}).get("actors", []):
                        raise ConnectionLost(addr)
                    self._dead_worker_addrs.discard(addr)
                except Exception:  # noqa: BLE001 - genuinely dead
                    # NO clients.drop here: the pooled connection may be
                    # carrying another actor's live traffic to a recycled
                    # port; dropping it would fail those calls.
                    st.address = None
                    st.stale_spins += 1
                    if st.stale_spins > 10:   # ~30s of stale ALIVE replies
                        for task, _ in batch:
                            self._fail_actor_call(task, ActorError(
                                st.actor_id,
                                "actor worker is dead (no restart "
                                "observed)"))
                        return
                    await asyncio.sleep(1.0)
                    continue
            st.stale_spins = 0
            # seq_floor: the lowest UNACKED seqno — the receiver's
            # baseline for a first-contact caller, and its fast-forward
            # point past seqnos that will never arrive (terminally failed
            # calls).  Without it, a reordered FIRST batch (socket
            # recreate mid-burst) set the baseline at its own seqnos and
            # earlier calls were executed as if they were stale retries.
            with st.submit_lock:
                floor = min(st.inflight_seqs) if st.inflight_seqs else 0
            for t, _ in batch:
                t.header["seq_floor"] = floor
            try:
                if len(batch) == 1:
                    task, _ = batch[0]
                    reply, rblobs = await self._actor_call_with_resend(
                        addr, "actor_call", task.header, task.blobs)
                    self._on_task_reply(task, reply, rblobs)
                    return
                headers = [{**t.header, "nframes": len(t.blobs)}
                           for t, _ in batch]
                blobs: list = []
                for t, _ in batch:
                    blobs.extend(t.blobs)
                reply, rblobs = await self._actor_call_with_resend(
                    addr, "actor_call_batch", {"calls": headers}, blobs)
            except (ConnectionLost, RemoteError):
                if st.address == addr:
                    st.address = None
                # In-flight calls lost: resend only those with an explicit
                # retry budget (ray: max_task_retries; default 0 =
                # at-most-once → actor error).
                still = []
                for task, r in batch:
                    if r > 0:
                        still.append((task, r - 1))
                    else:
                        self._fail_actor_call(task, ActorError(
                            st.actor_id, "actor worker connection lost"))
                        # A dead seqno must leave the floor NOW: resent
                        # survivors stamped with a floor that includes it
                        # would park at the receiver forever behind a gap
                        # that never fills.
                        st.inflight_seqs.discard(
                            task.header.get("seqno", 0))
                if not still:
                    return
                batch = still
                continue
            offset = 0
            for (task, _), tr in zip(batch, reply["replies"]):
                n = tr.pop("nblobs")
                self._on_task_reply(task, tr, rblobs[offset:offset + n])
                offset += n
            return

    async def _actor_call_with_resend(self, addr: str, method: str,
                                      header: dict, blobs: list):
        """Actor-call transport with a lost-reply watchdog (the round-9
        "dropped actor reply" window): after actor_reply_resend_s with
        no reply, RESEND the same msgid+seqnos (rpc call_with_resend —
        the pending future stays registered across deadlines, so a
        large reply still in flight when the watchdog fires lands
        instead of being dropped and tombstoning as REPLY_EVICTED on
        the resend, mirroring the fused path's resend_direct).  The
        receiver's at-most-once machinery makes the resend safe — a
        seqno whose execution completed serves the cached reply, one
        still in flight attaches the resend to the shared execution
        future (rpc_actor_call stale-seqno path), so stateful methods
        never double-apply.  Genuine worker death still surfaces as
        ConnectionLost via the death broadcast (clients.drop fails the
        pending future), which breaks the wait into the caller's
        retry/fail handling."""
        resend_s = self.config.actor_reply_resend_s
        cli = self.clients.get(addr)
        if not resend_s or resend_s <= 0:
            return await cli.call(method, header, blobs)
        return await cli.call_with_resend(method, header, blobs,
                                          resend_s=resend_s)

    async def _resolve_actor_addr(self, st: ActorSubmitState) -> str | None:
        if st.address:
            return st.address
        if st.resolving is None or st.resolving.done():
            st.resolving = self.loop.create_task(self._do_resolve(st))
        await asyncio.shield(st.resolving)
        return st.address

    async def _do_resolve(self, st: ActorSubmitState) -> None:
        # Never overtake our own (batched, possibly still queued)
        # registration: UNKNOWN from the controller reads as dead.
        await self._actor_regs_settled()
        if st.dead:
            return          # registration flush failed; cause is set
        reply, _ = await self.clients.get(self.controller_addr).call(
            "get_actor_info",
            {"actor_id": st.actor_id, "wait": True, "timeout": 120.0},
            timeout=150.0)
        # NOTE: no _revive_addr here — a controller ALIVE reply can be
        # stale (death report still in flight); only the supervising
        # agent's lease grant or a fresh alive EVENT proves liveness.
        if reply.get("state") == "ALIVE":
            st.address = reply["address"]
        elif reply.get("state") in ("DEAD", "UNKNOWN"):
            st.dead = True
            st.death_cause = reply.get("cause") or reply.get("state", "")

    async def _on_actor_event(self, _topic: str, payload: dict) -> None:
        if payload.get("batch"):
            # A scheduler wave publishes its whole ALIVE storm as ONE
            # message (controller._run_actor_wave).
            for ev in payload["batch"]:
                await self._on_actor_event(_topic, ev)
            return
        actor_id = payload.get("actor_id", "")
        ev = payload.get("event")
        if ev == "dead":
            # Even with no submit state (actor created here, never
            # called), the death must release this process's
            # creation-arg pins.
            self._release_creation_borrows(actor_id)
        st = self.actor_states.get(actor_id)
        if st is None:
            return
        if ev == "alive":
            self._revive_addr(payload["address"])
            st.address = payload["address"]
            st.dead = False
            return
        old = st.address
        st.address = None
        if ev == "dead":
            st.dead = True
            st.death_cause = payload.get("cause", "")
        # zmq DEALER sockets never surface peer death; dropping the client
        # fails its in-flight futures with ConnectionLost so callers waiting
        # on a dead actor's reply unblock (ray: worker failure pubsub →
        # ActorTaskSubmitter::DisconnectActor).
        if old:
            self.clients.drop(old)

    def create_actor(self, cls: Any, args: tuple, kwargs: dict,
                     options: dict) -> tuple[str, bool]:
        """Returns (actor_id, existing) — existing=True when get_if_exists
        matched a live actor instead of creating one."""
        fid = self.export_function(cls)
        actor_id = ActorID.from_random().hex()
        resources = dict(options.get("resources") or {})
        resources.setdefault("CPU", options.get("num_cpus", 1))
        if options.get("num_tpus"):
            resources["TPU"] = options["num_tpus"]
        task_id = TaskID.from_random()
        # Creation-arg borrow pins live as long as the actor: the instance
        # typically retains deserialized refs, and there is no reply-time
        # held-ref report for creation tasks.  Released when this process
        # kills the actor or observes its death.
        header, blobs, creation_borrows = self._build_task_payload(
            task_id.binary(), fid, args, kwargs, 0, resources,
            options.get("bundle_key"), options)
        header.update({
            "function_id": fid,
            "class_name": getattr(cls, "__name__", "?"),
            "max_concurrency": options.get("max_concurrency"),
            "is_async": bool(options.get("is_async")),
        })
        if options.get("concurrency_groups"):
            header["concurrency_groups"] = dict(
                options["concurrency_groups"])
            header["method_groups"] = dict(
                options.get("method_groups") or {})
        waves = os.environ.get("RAY_TPU_ACTOR_WAVES", "1") \
            not in ("0", "false")
        reg = {"actor_id": actor_id, "creation_header": header,
               "owner_addr": self.address, "resources": resources,
               "max_restarts": options.get("max_restarts", 0),
               "name": options.get("name"),
               "namespace": options.get("namespace", self.namespace),
               "get_if_exists": options.get("get_if_exists", False),
               "detached": options.get("lifetime") == "detached",
               "pg_id": options.get("pg_id"),
               "bundle_index": options.get("bundle_index", -1),
               "affinity_node_id": options.get("affinity_node_id"),
               "label_hard": options.get("label_hard"),
               "label_soft": options.get("label_soft"),
               "affinity_soft": options.get("affinity_soft", False),
               "wave": waves}
        if waves and not reg["name"]:
            # Burst fusion: an UNNAMED actor's registration reply is
            # fully determined client-side (the id is ours; there is no
            # name-taken outcome), so don't pay one controller RT per
            # actor — enqueue, let the loop-side flusher coalesce the
            # burst into ONE create_actors RT, and return immediately.
            # Later RPCs naming the actor gate on _actor_regs_settled so
            # they can never overtake the registration.
            if creation_borrows:
                self.actor_creation_borrows[actor_id] = creation_borrows
            self._enqueue_actor_registration(reg, blobs)
            return actor_id, False
        try:
            reply, _ = self.call(
                self.controller_addr, "create_actor", reg,
                blobs, timeout=120.0)
            if reply.get("error"):
                raise ValueError(reply["error"])
        except BaseException:
            # Failed creation (name taken, controller error, timeout):
            # the creation payload is discarded, so its pins must go too.
            for oid, owner in creation_borrows:
                self._release_borrow(oid, owner)
            raise
        existing = bool(reply.get("existing"))
        if creation_borrows:
            if existing:
                # get_if_exists hit: the creation payload is discarded, so
                # its pins must be released immediately.
                for oid, owner in creation_borrows:
                    self._release_borrow(oid, owner)
            else:
                self.actor_creation_borrows[reply["actor_id"]] = \
                    creation_borrows
        return reply["actor_id"], existing

    def _release_creation_borrows(self, actor_id: str) -> None:
        for oid, owner in self.actor_creation_borrows.pop(actor_id, ()):
            self._release_borrow(oid, owner)

    # ----------------------- batched actor registration (wave fusion)
    def _enqueue_actor_registration(self, reg: dict, blobs: list) -> None:
        with self._actor_reg_lock:
            self._actor_reg_batch.append((reg, blobs))
        self._post_to_loop(self._ensure_actor_reg_flusher)

    def _ensure_actor_reg_flusher(self) -> None:
        """Loop-side: make sure a flusher task is draining the batch."""
        if self._actor_reg_task is None or self._actor_reg_task.done():
            self._actor_reg_task = self.loop.create_task(
                self._flush_actor_regs())

    async def _flush_actor_regs(self) -> None:
        """Drain enqueued registrations, ONE create_actors RPC per drain.
        Registrations arriving while a flush RPC is in flight pile up
        and ride the next drain — burst size tracks controller latency
        automatically (the call_and_wait fusion shape)."""
        while True:
            with self._actor_reg_lock:
                batch, self._actor_reg_batch = self._actor_reg_batch, []
            if not batch:
                return
            t0 = time.time()
            header = {"actors": [dict(reg, nblobs=len(blobs))
                                 for reg, blobs in batch]}
            frames = [f for _reg, blobs in batch for f in blobs]
            try:
                await self.clients.get(self.controller_addr).call(
                    "create_actors", header, frames, timeout=120.0)
            except Exception as e:  # noqa: BLE001
                # The registrations never reached the controller: fail
                # the handles fast (resolvers see dead, not a 120s park)
                # and drop the creation-arg pins.
                logger.warning("batched actor registration failed: %r", e)
                for reg, _blobs in batch:
                    st = self._actor_state(reg["actor_id"])
                    st.dead = True
                    st.death_cause = f"actor registration failed: {e!r}"
                    self._release_creation_borrows(reg["actor_id"])
            spans.emit("actor.submit", t0, attrs={"count": len(batch)})

    async def _actor_regs_settled(self) -> None:
        """Wait until every enqueued registration has been flushed: an
        RPC naming the actor (resolve, kill) must never overtake its own
        registration on the controller connection."""
        while True:
            t = self._actor_reg_task
            if t is not None and not t.done():
                await asyncio.shield(t)
                continue
            with self._actor_reg_lock:
                if not self._actor_reg_batch:
                    return
            self._ensure_actor_reg_flusher()

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        async def _kill():
            # Bounded settle: the ordering guard must not chain the
            # flusher's full RPC timeout in front of the kill — with an
            # unreachable controller the remove fails anyway, and a
            # remove racing an undelivered registration is a no-op.
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._actor_regs_settled(), 30.0)
            return await self.acall(self.controller_addr, "remove_actor",
                                    {"actor_id": actor_id}, timeout=30.0)
        self.run(_kill())
        st = self.actor_states.get(actor_id)
        if st:
            st.dead = True
            st.address = None
            st.death_cause = "killed"
        self._release_creation_borrows(actor_id)

    def kill_actor_async(self, actor_id: str) -> None:
        """Fire-and-forget kill used by ActorHandle GC (must not block in
        __del__, which can run on any thread including the IO loop's)."""
        loop = self.loop
        if loop is None or self._shutdown.is_set():
            return

        def _go():
            async def _run():
                await self._actor_regs_settled()
                await self.acall(
                    self.controller_addr, "remove_actor",
                    {"actor_id": actor_id, "cause": "handle out of scope"},
                    timeout=30.0)
            loop.create_task(_run())
            self._release_creation_borrows(actor_id)
        try:
            loop.call_soon_threadsafe(_go)
        except RuntimeError:
            pass

    # ------------------------------------------------------------- cancel
    def cancel_task(self, ref: ObjectRef) -> None:
        async def _cancel():
            try:
                await self.clients.get(ref.owner_addr or self.address).notify(
                    "cancel_task", {"object_id": ref.hex()})
            except Exception:  # noqa: BLE001
                pass
        self.run(_cancel())

    async def rpc_cancel_task(self, h: dict, _b: list) -> dict:
        # Owner-side: mark queued tasks cancelled; cancel running async ones.
        oid = bytes.fromhex(h["object_id"])
        for key, q in self.lease_manager.queues.items():
            for t in list(q):
                if oid in t.return_ids:
                    q.remove(t)
                    err = TaskCancelledError(t.task_id.hex())
                    for rid in t.return_ids:
                        self._resolve_error(rid, err)
                    return {}
        atask = self._running_async.get(oid)
        if atask:
            atask.cancel()
        return {}

    # ------------------------------------------------------------- control
    async def rpc_worker_died(self, h: dict, _b: list) -> dict:
        addr = h.get("worker_addr", "")
        if h.get("oom"):
            # Remembered so the push-failure error names the real killer
            # (ray: OOM kills surface as OutOfMemoryError, not a generic
            # worker crash).
            self._oom_worker_addrs.add(addr)
        # Dead-address registry: zmq DEALERs never surface peer death, so
        # a LATER send to this address would create a fresh silently-
        # hanging connection.  Sends check this set first (ray: worker
        # failure pubsub gates the submitter the same way).
        if addr:
            self._mark_addr_dead(addr)
        self.clients.drop(addr)
        return {}

    def _revive_addr(self, addr: str) -> None:
        """A live worker provably exists at this address now (lease
        granted on it / actor alive there): clear stale death marks so a
        reused ephemeral port isn't treated as dead forever.  Purge the
        eviction ring too — a stale ring entry would later pop and
        un-mark the address if it dies AGAIN in the meantime."""
        self._dead_worker_addrs.discard(addr)
        self._oom_worker_addrs.discard(addr)
        if addr in self._dead_addr_order:
            self._dead_addr_order.remove(addr)

    async def rpc_exit_worker(self, h: dict, _b: list) -> dict:
        logger.info("worker exiting: %s", h.get("reason"))
        self.loop.call_later(0.05, self._shutdown.set)
        if h.get("hard"):
            self.loop.call_later(0.1, lambda: os._exit(0))
        return {}

    async def rpc_ping(self, h: dict, _b: list) -> dict:
        return {"worker_id": self.worker_id,
                "actors": list(self.actors_hosted)}

    async def rpc_failpoints(self, h: dict, _b: list) -> dict:
        """Runtime fault-injection control verb (see _private/failpoints):
        arm/clear/read the deterministic failpoint table of THIS process
        without restarting it."""
        return failpoints.control(h)

    async def rpc_spans(self, h: dict, _b: list) -> dict:
        """Flight-recorder harvest verb (see _private/spans): read/clear
        THIS process's span ring buffer."""
        return spans.control(h)

    async def rpc_memory(self, h: dict, _b: list) -> dict:
        """Object-ledger harvest verb (see _private/memledger): THIS
        process's owner-side reference table + ledger annotations."""
        return memledger.control(h)

    async def rpc_telemetry(self, h: dict, _b: list) -> dict:
        """Telemetry-timeline harvest verb (see _private/telemetry):
        THIS process's metrics-snapshot ring."""
        from ray_tpu._private import telemetry

        return telemetry.control(h)

    # ------------------------------------------------------------ telemetry
    def _record_event(self, task_id: str, state: str, name: str = "",
                      trace: dict | None = None) -> None:
        tc = trace or self.current_trace
        tag = self._event_tag
        if tag is None:
            # worker/node ids are fixed after start; slice them once
            # (this runs twice per task on the submit hot path).
            tag = self._event_tag = (self.worker_id[:12],
                                     self.node_id[:12])
        self._task_events.append(
            {"task_id": task_id, "state": state, "name": name,
             "t": time.time(), "worker": tag[0], "node": tag[1],
             "trace_id": tc["trace_id"][:16] if tc else "",
             # Parent span for the OTLP export bridge (utils/tracing.py):
             # present only on events of tasks submitted inside tasks.
             "parent": (tc.get("parent_span") or "")[:16] if tc else ""})
        if len(self._task_events) > self.config.task_event_buffer_size:
            self._task_events = self._task_events[-self.config.
                                                  task_event_buffer_size:]

    async def _event_flush_loop(self) -> None:
        """Push buffered task events to the controller timeline
        (ray: TaskEventBuffer task_event_buffer.h:206)."""
        while True:
            await asyncio.sleep(1.0)
            if self._task_events:
                events, self._task_events = self._task_events, []
                try:
                    await self.clients.get(self.controller_addr).notify(
                        "push_task_events", {"events": events})
                except Exception:  # noqa: BLE001
                    pass
