"""Object storage: per-node store + per-worker in-process memory store.

Analog of the reference's two-tier object storage:
  - small objects live in the owner's in-process memory store and travel
    inline in RPC replies (ray: CoreWorkerMemoryStore memory_store.h:43,
    max_direct_call_object_size)
  - large objects live in a per-node store served by the node agent, located
    via the owner, and pulled node-to-node in chunks
    (ray: plasma store store_runner.h:14 + ObjectManager::Push
    object_manager.cc:339, 64MB chunks)

The node store backend is pluggable: `native/store.cc` provides the
shared-memory arena (mmap + offset allocator) used when built; a dict-backed
fallback keeps the runtime functional without the native build.  Workers on
the same host read sealed objects zero-copy out of the mmap'd arena.
"""
from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any

from ray_tpu._private import failpoints

logger = logging.getLogger(__name__)


@dataclass
class MemoryEntry:
    event: asyncio.Event
    # Exactly one of (value-present, frames, error, locations) materializes.
    has_value: bool = False
    value: Any = None
    frames: list[bytes] | None = None
    error: BaseException | None = None
    locations: list[str] = field(default_factory=list)  # node agent addrs
    # Lazily-attached wakeup for SYNC getters waiting off the IO loop
    # (worker.py _get_objects_fast): fill sites publish fields, then
    # wake() both waiter kinds.  Plain threading.Event — safe to set
    # from the loop thread, waitable from any.
    t_event: "Any" = None

    def resolved(self) -> bool:
        return (self.has_value or self.error is not None
                or self.frames is not None or bool(self.locations))

    def wake(self) -> None:
        self.event.set()
        t = self.t_event
        if t is not None:
            t.set()


class MemoryStore:
    """In-process store of object id -> resolved value/frames/locations.

    Futures-based: getters wait on the entry's event until the task that
    produces the object completes (ray: GetRequest in memory_store.cc).
    Entry creation is thread-safe: the IO loop and sync caller threads
    (put/get fast paths) both materialize entries.
    """

    def __init__(self) -> None:
        import threading

        self._entries: dict[bytes, MemoryEntry] = {}
        self._lock = threading.Lock()

    def entry(self, object_id: bytes) -> MemoryEntry:
        e = self._entries.get(object_id)
        if e is None:
            with self._lock:
                e = self._entries.get(object_id)
                if e is None:
                    e = MemoryEntry(event=asyncio.Event())
                    self._entries[object_id] = e
        return e

    def get_if_exists(self, object_id: bytes) -> MemoryEntry | None:
        return self._entries.get(object_id)

    def reset(self, object_id: bytes) -> MemoryEntry:
        """Clear an entry for re-resolution (lineage reconstruction)
        WITHOUT replacing the object: existing waiters keep their
        reference and wake on the refill."""
        e = self.entry(object_id)
        e.has_value, e.value, e.frames, e.error = False, None, None, None
        e.locations = []
        e.event.clear()
        if e.t_event is not None:
            e.t_event.clear()
        return e

    def put_value(self, object_id: bytes, value: Any) -> None:
        e = self.entry(object_id)
        e.has_value = True
        e.value = value
        e.wake()

    def put_frames(self, object_id: bytes, frames: list[bytes]) -> None:
        e = self.entry(object_id)
        e.frames = frames
        e.wake()

    def put_error(self, object_id: bytes, err: BaseException) -> None:
        e = self.entry(object_id)
        e.error = err
        e.wake()

    def put_locations(self, object_id: bytes, locations: list[str]) -> None:
        e = self.entry(object_id)
        e.locations = list(locations)
        e.wake()

    def ready(self, object_id: bytes) -> bool:
        e = self._entries.get(object_id)
        return e is not None and e.event.is_set()

    def delete(self, object_id: bytes) -> None:
        self._entries.pop(object_id, None)

    def __len__(self) -> int:
        return len(self._entries)


class _DictBackend:
    """Fallback node-store backend when the native arena isn't built."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.used = 0
        self._data: dict[bytes, list[bytes]] = {}
        self._lru: dict[bytes, float] = {}
        self._pinned: dict[bytes, int] = {}

    @property
    def shm_name(self) -> str:
        return ""

    def put(self, oid: bytes, frames: list[bytes]) -> bool:
        size = sum(len(f) for f in frames)
        if oid in self._data:
            return True
        if self.used + size > self.capacity:
            # No implicit eviction (data loss); the StoreRunner spills the
            # LRU object to disk and retries (plasma → spill discipline).
            return False
        self._data[oid] = frames
        self._lru[oid] = time.monotonic()
        self.used += size
        return True

    def get(self, oid: bytes) -> list[bytes] | None:
        frames = self._data.get(oid)
        if frames is not None:
            self._lru[oid] = time.monotonic()
        return frames

    def contains(self, oid: bytes) -> bool:
        return oid in self._data

    def delete(self, oid: bytes) -> bool:
        frames = self._data.pop(oid, None)
        self._lru.pop(oid, None)
        self._pinned.pop(oid, None)
        if frames is not None:
            self.used -= sum(len(f) for f in frames)
        return True

    def pin(self, oid: bytes, delta: int) -> None:
        self._pinned[oid] = max(0, self._pinned.get(oid, 0) + delta)

    def scan_objects(self) -> list[dict]:
        """Ledger view (native scan_objects shape).  The dict backend
        has no creating state or pid attribution — every entry reads as
        sealed, created by this process."""
        return [{"object_id": oid,
                 "size": sum(len(f) for f in frames),
                 "lru_tick": self._lru.get(oid, 0.0),
                 "sealed": True,
                 "pins": self._pinned.get(oid, 0),
                 "creator_pid": os.getpid()}
                for oid, frames in self._data.items()]

    def scan_pins(self) -> list[tuple[bytes, int]]:
        return []      # no pid-attributed pins without the native arena

    def oldest(self) -> bytes | None:
        """LRU unpinned object id — the next spill candidate
        (ray: plasma LRU eviction_policy.h:105)."""
        candidates = [oid for oid in self._lru
                      if self._pinned.get(oid, 0) == 0]
        if not candidates:
            return None
        return min(candidates, key=lambda o: self._lru[o])

    def stats(self) -> dict:
        return {"used": self.used, "capacity": self.capacity,
                "num_objects": len(self._data)}

    def close(self) -> None:
        self._data.clear()


def _spill_size(path: str) -> int:
    try:
        return os.path.getsize(path)
    except OSError:
        return 0


def _make_backend(node_id: str, capacity: int, config=None):
    try:
        from ray_tpu._private.native_store import NativeStoreBackend

        return NativeStoreBackend(node_id, capacity, config=config)
    except Exception:  # noqa: BLE001 - native build absent is fine
        return _DictBackend(capacity)


class StoreRunner:
    """Node-agent-side object store service (ray: PlasmaStoreRunner embedded
    in the raylet, store_runner.h:14) with disk spilling: when the arena is
    full, LRU objects spill to files and restore on demand (ray:
    LocalObjectManager local_object_manager.h:41 + external_storage.py)."""

    def __init__(self, node_id: str, config):
        import tempfile

        self.node_id = node_id
        self.config = config
        self.backend = _make_backend(node_id, config.object_store_memory,
                                     config=config)
        self._clients = None
        self.spill_dir = os.path.join(
            tempfile.gettempdir(),
            f"ray_tpu_spill_{node_id[:8]}_{os.getpid()}")
        self.spilled: dict[bytes, str] = {}     # oid -> file path
        self.spilled_bytes = 0
        # Deletes refused because a zero-copy reader still pins the object
        # are retried from the agent's reaper loop (retry_deletes); without
        # this the dead object would linger, get spilled under pressure,
        # and leak on disk forever.
        self._pending_deletes: set[bytes] = set()
        # Serializes spill-to-disk decisions across concurrent async puts
        # (the file writes themselves run off-loop in a thread).
        self._spill_lock = asyncio.Lock()
        # In-flight pull dedup: concurrent gets of one remote object join
        # a single transfer (and never mistake a sibling's creating-state
        # allocation for a full arena).
        self._pulling: dict[bytes, asyncio.Future] = {}
        # Agent addresses of DEAD nodes (maintained by the node agent
        # from controller "node" events): transfers skip them instead of
        # waiting out the RPC timeout against a silent zmq reconnect.
        self.dead_addrs: set[str] = set()
        # Same-host peer arenas (shm name -> mapped Arena): multiple
        # node agents on one host (in-process Cluster, multi-agent
        # deployments) pull from each other with ONE streaming-kernel
        # copy instead of the zmq chunk protocol (see _pull_same_host).
        self._peer_arenas: dict[str, Any] = {}

    @property
    def shm_name(self) -> str:
        return self.backend.shm_name

    def register_handlers(self, server, clients) -> None:
        self._clients = clients
        server.register("store_put", self.rpc_store_put)
        server.register("store_get", self.rpc_store_get)
        server.register("store_get_meta", self.rpc_store_get_meta)
        server.register("store_get_chunk", self.rpc_store_get_chunk)
        server.register("store_contains", self.rpc_store_contains)
        server.register("store_delete", self.rpc_store_delete)
        server.register("store_pull", self.rpc_store_pull)
        server.register("store_stats", self.rpc_store_stats)

    # -------------------------------------------------------------- spill
    def _write_spill_file(self, oid: bytes, frames: list) -> tuple[str, int]:
        """Serialize a frame bundle to the spill dir; returns (path, bytes).

        The on-disk layout is IDENTICAL to the arena bundle layout
        (aligned frame offsets): chunked node-to-node pulls serve raw
        slices from either source interchangeably."""
        import struct as _struct

        from ray_tpu._private.native_store import _bundle_layout

        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, oid.hex())
        lens = [len(fr) for fr in frames]
        total, offsets = _bundle_layout(lens)
        with open(path, "wb") as f:
            f.write(_struct.pack("<I", len(frames)))
            f.write(_struct.pack(f"<{len(lens)}Q", *lens))
            for fr, fo in zip(frames, offsets):
                f.seek(fo)
                f.write(fr)
            f.truncate(total)
        return path, total

    async def _spill_one(self) -> bool:
        """Write the LRU object's frames to disk and drop it from memory.
        The file write runs off the event loop: spilling a few GB
        synchronously would stall the agent's heartbeat loop past
        node_death_timeout_s and turn memory pressure into node death."""
        oid = self.backend.oldest()
        if oid is None:
            return False
        if oid in self._pending_deletes:
            # Tombstoned (delete was refused while pinned): free it now
            # instead of wasting disk on a dead object.
            if self.backend.delete(oid):
                self._pending_deletes.discard(oid)
                return True
        copy_fn = getattr(self.backend, "get_bundle_copy", None)
        if copy_fn is not None:
            # Explicitly-unpinned copy read: the subsequent delete must not
            # depend on GC collecting a zero-copy view's finalizer.
            data = copy_fn(oid)
            if data is None:
                return False
            path, size = await asyncio.to_thread(self._write_spill_raw,
                                                 oid, data)
        else:
            frames = self.backend.get(oid)
            if frames is None:
                return False
            path, size = await asyncio.to_thread(self._write_spill_file,
                                                 oid, frames)
            del frames      # dict backend: plain bytes, nothing pinned
        if not self.backend.contains(oid):
            # Deleted while the file write was in flight: the object is
            # dead — registering the spill file would resurrect it (and
            # leak the file forever).  Memory was freed by the delete, so
            # this still counts as progress for the caller's retry loop.
            self._pending_deletes.discard(oid)
            try:
                os.unlink(path)
            except OSError:
                pass
            return True
        if not self.backend.delete(oid):
            # Raced with a reader pinning it: the arena copy stays
            # authoritative; drop the file so nothing double-counts.
            try:
                os.unlink(path)
            except OSError:
                pass
            return False
        self.spilled[oid] = path
        self.spilled_bytes += size
        logger.info("spilled %s (%d B) to %s", oid.hex()[:12], size, path)
        return True

    def _write_spill_raw(self, oid: bytes, data: bytes) -> tuple[str, int]:
        """Write an already-laid-out frame bundle (the arena's raw bytes)
        straight to the spill file — the two layouts are identical."""
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, oid.hex())
        with open(path, "wb") as f:
            f.write(data)
        return path, len(data)

    def _read_spilled(self, oid: bytes) -> list[bytes] | None:
        path = self.spilled.get(oid)
        if path is None:
            return None
        import struct as _struct

        from ray_tpu._private.native_store import _bundle_layout

        try:
            with open(path, "rb") as f:
                (n,) = _struct.unpack("<I", f.read(4))
                lens = _struct.unpack(f"<{n}Q", f.read(8 * n))
                _, offsets = _bundle_layout(list(lens))
                out = []
                for ln, fo in zip(lens, offsets):
                    f.seek(fo)
                    out.append(f.read(ln))
                return out
        except OSError:
            return None

    def _delete_spilled(self, oid: bytes) -> None:
        path = self.spilled.pop(oid, None)
        if path:
            try:
                self.spilled_bytes -= os.path.getsize(path)
                os.unlink(path)
            except OSError:
                pass

    async def put_with_spill(self, oid: bytes, frames: list) -> bool:
        """Insert, spilling LRU objects to disk until it fits (ray: plasma
        CreateRequestQueue backpressure → spill).  All disk I/O runs off
        the event loop (see _spill_one): heartbeats share this loop."""
        # Duplicate puts (client retry, task re-execution) are a success,
        # NOT a reason to spill: the native backend's put returns False
        # for already-present ids exactly like for a full arena.
        if self.backend.contains(oid) or oid in self.spilled:
            return True
        if self.backend.put(oid, frames):
            return True
        async with self._spill_lock:
            # Re-check after the lock wait: a concurrent duplicate put may
            # have landed this oid (in arena or on disk) meanwhile.
            if self.backend.contains(oid) or oid in self.spilled:
                return True
            if self.backend.put(oid, frames):
                return True
            for _ in range(4096):
                if not await self._spill_one():
                    break
                if self.backend.put(oid, frames):
                    return True
            # Arena can't hold it even after spilling: spill the new
            # object itself straight to disk.
            path, size = await asyncio.to_thread(self._write_spill_file,
                                                 oid, frames)
            self.spilled[oid] = path
            self.spilled_bytes += size
            return True

    async def rpc_store_put(self, h: dict, blobs: list) -> dict:
        ok = await self.put_with_spill(bytes.fromhex(h["object_id"]),
                                       list(blobs))
        return {"ok": ok}

    async def rpc_store_get(self, h: dict, _b: list) -> tuple[dict, list]:
        oid = bytes.fromhex(h["object_id"])
        frames = self.backend.get(oid)
        if frames is None:
            # Restore from disk (ray: spilled_object_reader.cc); best
            # effort re-insert so repeat readers hit memory.  Off-loop:
            # restoring a multi-GB object inline would stall heartbeats.
            restored = await asyncio.to_thread(self._read_spilled, oid)
            if restored is None:
                return {"found": False}, []
            if self.backend.put(oid, restored):
                self._delete_spilled(oid)
            return {"found": True}, restored
        return {"found": True}, list(frames)

    async def rpc_store_contains(self, h: dict, _b: list) -> dict:
        return {"found": self.backend.contains(bytes.fromhex(h["object_id"]))}

    async def rpc_store_delete(self, h: dict, _b: list) -> dict:
        oid = bytes.fromhex(h["object_id"])
        if not self.backend.delete(oid):
            # Refused: a zero-copy reader still pins it.  Tombstone so the
            # reaper retries after the pin releases — otherwise the dead
            # object lingers, gets spilled under pressure, and leaks.
            self._pending_deletes.add(oid)
        self._delete_spilled(oid)
        return {}

    def retry_deletes(self) -> int:
        """Retry tombstoned deletes (called from the agent's reaper loop,
        after sweep_dead has reclaimed crashed readers' pins)."""
        done = 0
        for oid in list(self._pending_deletes):
            if self.backend.delete(oid):
                self._pending_deletes.discard(oid)
                self._delete_spilled(oid)
                done += 1
        return done

    # --------------------------------------------- node-to-node transfer
    async def rpc_store_get_meta(self, h: dict, _b: list) -> dict:
        """Bundle size for a chunked pull.  Native arenas also advertise
        their shm name so a same-host puller can take the direct
        cross-arena copy path."""
        oid = bytes.fromhex(h["object_id"])
        raw_fn = getattr(self.backend, "get_raw", None)
        if raw_fn is not None:
            raw = raw_fn(oid)
            if raw is not None:
                return {"found": True, "size": len(raw),
                        "shm": getattr(self.backend, "shm_name", None)}
        if oid in self.spilled:
            try:
                return {"found": True,
                        "size": os.path.getsize(self.spilled[oid]),
                        "spilled": True}
            except OSError:
                pass
        return {"found": self.backend.contains(oid)}

    async def rpc_store_get_chunk(self, h: dict,
                                  _b: list) -> tuple[dict, list]:
        """One raw slice of the frame bundle (pinned zero-copy view)."""
        oid = bytes.fromhex(h["object_id"])
        off, length = h["offset"], h["length"]
        # Failpoint window: the SOURCE node serving one chunk of a
        # multi-chunk transfer (crash = source dies mid-pull; the puller
        # must fall back to other locations or lineage).
        if failpoints.ACTIVE:
            await failpoints.fire_async("store.serve_chunk")
        raw_fn = getattr(self.backend, "get_raw", None)
        raw = raw_fn(oid) if raw_fn is not None else None
        if raw is None:
            path = self.spilled.get(oid)
            if path is not None:
                def _read_range():
                    with open(path, "rb") as f:
                        f.seek(off)
                        return f.read(length)
                try:
                    # Off-loop: a 64MB synchronous read would stall every
                    # other RPC this agent serves.
                    data = await asyncio.to_thread(_read_range)
                    return {"found": True}, [data]
                except OSError:
                    pass
            return {"found": False}, []
        return {"found": True}, [raw[off:off + length]]

    def _peer_arena(self, shm: str):
        a = self._peer_arenas.get(shm)
        if a is None:
            from ray_tpu._private.native_store import Arena

            a = Arena(shm, create=False)
            self._peer_arenas[shm] = a
        return a

    async def _reserve_raw(self, oid: bytes, size: int) -> str:
        """create_raw with the make-room-by-spilling discipline of local
        puts (shared by the chunked and same-host pull paths).  Returns
        "created" | "present" | "fail".

        A create_raw failure has TWO causes and only one of them is
        capacity: a DUPLICATE id means another puller (possibly a
        worker's direct-shm pull — invisible to this agent's _pulling
        dedup) is assembling the same object right now.  Spilling in
        that case would futilely evict the whole arena (create_raw keeps
        failing on the duplicate), so wait for the sibling instead:
        "present" once it seals; retry the alloc if its creating block
        vanishes (aborted, or swept after a crash)."""
        peek = getattr(self.backend, "peek_raw", None)
        deadline = time.monotonic() + 120.0
        for _ in range(8192):
            if self.backend.contains(oid):
                return "present"
            if self.backend.create_raw(oid, size):
                return "created"
            if peek is not None and peek(oid):
                if time.monotonic() > deadline:
                    return "fail"
                await asyncio.sleep(0.05)
                continue
            async with self._spill_lock:
                if self.backend.create_raw(oid, size):
                    return "created"
                if not await self._spill_one():
                    return "fail"
        return "fail"

    async def _pull_same_host(self, oid: bytes, meta: dict) -> bool:
        """Same-host fast path: the source agent's arena is a /dev/shm
        file on THIS machine, so map it and stream the sealed bundle
        straight into the local arena — one non-temporal copy at memory
        bandwidth, zero zmq hops (the NCCL SHM-transport analog; the
        in-process test Cluster's "DCN" is exactly this shape).  The
        source-side read pin is the normal pid-attributed pin, so a
        crashed puller is swept like any dead reader.  Kill switch
        RAY_TPU_SHM_PULL=0 restores the chunk protocol."""
        shm = meta.get("shm")
        if (not shm or not hasattr(self.backend, "write_raw_from_addr")
                or os.environ.get("RAY_TPU_SHM_PULL", "1") == "0"):
            return False
        if not os.path.exists(os.path.join("/dev/shm", shm.lstrip("/"))):
            return False    # source arena is not on this host
        try:
            peer = self._peer_arena(shm)
            raw = peer.get_raw_addr(oid)
        except Exception:  # noqa: BLE001 - racing arena teardown
            stale = self._peer_arenas.pop(shm, None)
            if stale is not None:
                stale.close()
            return False
        if raw is None:
            return False
        src_addr, size, release = raw
        try:
            got = await self._reserve_raw(oid, size)
            if got == "present":
                return True       # a sibling pull landed it meanwhile
            if got != "created":
                return False
            def _copy() -> bool:
                return self.backend.write_raw_from_addr(
                    oid, 0, src_addr, size)
            # Off-loop above 8 MiB: even at streaming-kernel speed a
            # big bundle copy would stall every other RPC this agent
            # serves.
            ok = (await asyncio.to_thread(_copy)
                  if size > (8 << 20) else _copy())
            if ok:
                ok = self.backend.seal_raw(oid)
            if not ok:
                # Abort on ANY failure (copy or seal): a live agent's
                # creating-state block is invisible to the dead-pid
                # sweep, so a leftover would strand the allocation and
                # park every later _reserve_raw for this oid in its
                # wait-for-sibling loop.
                self.backend.abort_raw(oid)
            return ok
        except BaseException:
            self.backend.abort_raw(oid)
            raise
        finally:
            release()

    async def _pull_chunked(self, oid: bytes, addr: str,
                            size: int) -> bool:
        """Assemble a remote object from parallel chunk fetches directly
        into the local arena (ray: ObjectManager 64MB chunks, 8 in
        flight, object_manager.cc:508)."""
        chunk = self.config.transfer_chunk_bytes
        got = await self._reserve_raw(oid, size)
        if got == "present":
            return True           # a sibling pull landed it meanwhile
        if got != "created":
            return False
        sem = asyncio.Semaphore(self.config.transfer_chunks_in_flight)
        failed = asyncio.Event()

        async def fetch(off: int) -> None:
            async with sem:
                if failed.is_set():
                    return
                if addr in self.dead_addrs:
                    # Source died mid-pull: abandon NOW (a fresh client
                    # to the dead address would hang out the timeout).
                    failed.set()
                    return
                try:
                    reply, blobs = await self._clients.get(addr).call(
                        "store_get_chunk",
                        {"object_id": oid.hex(), "offset": off,
                         "length": min(chunk, size - off)}, timeout=120.0)
                except Exception:  # noqa: BLE001
                    failed.set()
                    return
                if not reply.get("found") or not self.backend.write_raw(
                        oid, off, blobs[0]):
                    failed.set()
                    return
                # Failpoint window: a chunk boundary of the PULLING node
                # — the destination block is creating-state; a crash here
                # leaves it for the dead-pid sweep.
                if failpoints.ACTIVE:
                    await failpoints.fire_async("store.pull_chunk")

        # return_exceptions: an exception escaping a fetch (e.g. an
        # injected store.pull_chunk error) must reach the abort below,
        # not propagate past it — a live process's creating-state block
        # is invisible to the dead-pid sweep and would leak forever.
        results = await asyncio.gather(
            *[fetch(off) for off in range(0, size, chunk)],
            return_exceptions=True)
        if any(isinstance(r, BaseException) for r in results):
            failed.set()
        if failed.is_set():
            self.backend.abort_raw(oid)
            return False
        if not self.backend.seal_raw(oid):
            # Same discipline as _pull_same_host: never leave a live
            # process's creating-state block behind.
            self.backend.abort_raw(oid)
            return False
        return True

    async def rpc_store_pull(self, h: dict, _b: list) -> dict:
        """Replicate an object from a remote node store into this one
        (ray: PullManager pull_manager.h:52 → ObjectManager::Push).
        Concurrent pulls of the same object coalesce."""
        oid = bytes.fromhex(h["object_id"])
        inflight = self._pulling.get(oid)
        if inflight is not None:
            return {"ok": await asyncio.shield(inflight)}
        fut = asyncio.get_running_loop().create_future()
        self._pulling[oid] = fut
        try:
            ok = await self._do_pull(oid, h)
        except BaseException:
            fut.set_result(False)
            raise
        else:
            fut.set_result(ok)
        finally:
            self._pulling.pop(oid, None)
        return {"ok": ok}

    async def _do_pull(self, oid: bytes, h: dict) -> bool:
        if self.backend.contains(oid):
            return True
        if oid in self.spilled:
            # Already on local disk: restore instead of a network fetch.
            restored = await asyncio.to_thread(self._read_spilled, oid)
            if restored is not None:
                if self.backend.put(oid, restored):
                    self._delete_spilled(oid)
                return True
        chunked_ok = hasattr(self.backend, "create_raw")
        for addr in h.get("from", []):
            if addr in self.dead_addrs:
                continue
            if chunked_ok:
                try:
                    meta, _ = await self._clients.get(addr).call(
                        "store_get_meta", {"object_id": h["object_id"]},
                        timeout=30.0)
                except Exception:  # noqa: BLE001
                    continue
                if not meta.get("found"):
                    continue
                size = meta.get("size")
                if (size and size <= self.config.object_store_memory
                        and await self._pull_same_host(oid, meta)):
                    return True
                if (size and size > self.config.transfer_chunk_bytes
                        and size <= self.config.object_store_memory
                        and await self._pull_chunked(oid, addr, size)):
                    return True
                # Fall through to the whole-object path: it handles
                # objects larger than the arena (spill-to-disk landing)
                # and transient chunk failures.
            if addr in self.dead_addrs:
                # The source died DURING the chunked attempt above: a
                # whole-object retry against it would burn the full RPC
                # timeout for nothing.
                continue
            try:
                reply, blobs = await self._clients.get(addr).call(
                    "store_get", {"object_id": h["object_id"]}, timeout=60.0)
            except Exception:  # noqa: BLE001
                continue
            if reply.get("found"):
                return await self.put_with_spill(oid, blobs)
        return False

    def memory_report(self, limit: int = 5000) -> dict:
        """Node-store half of the `memory` verb: every arena entry with
        size/pins/creator-pid attribution (native scan; the dict backend
        degrades to sizes only), plus spill state.  Bounded like the
        ledger reply — biggest rows survive, the drop count is
        reported."""
        entries = []
        scan = getattr(self.backend, "scan_objects", None)
        if scan is not None:
            try:
                entries = scan()
            except Exception:  # noqa: BLE001 - racing close
                entries = []
        # Prefault claims (native_store rt_store_prefault_free: the
        # 0xFE+"prefault" id namespace) are transient runtime-internal
        # allocations, not objects — a scan racing a worker's arena
        # warm-up must not report a phantom 128 MiB unowned block.
        entries = [e for e in entries
                   if not e["object_id"].startswith(b"\xfeprefault")]
        truncated = 0
        if len(entries) > limit:
            entries.sort(key=lambda e: -e["size"])
            truncated = len(entries) - limit
            entries = entries[:limit]
        pin_scan = getattr(self.backend, "scan_pins", None)
        pins: list = []
        if pin_scan is not None:
            try:
                pins = pin_scan()
            except Exception:  # noqa: BLE001
                pins = []
        pin_pids: dict[str, list[int]] = {}
        for oid, pid in pins:
            pin_pids.setdefault(oid.hex(), []).append(pid)
        # Creator liveness is LOCAL-host truth (creators map this
        # host's arena), answered here so the harvest side can gate the
        # unreachable-owner gauge on it without remote pid access.
        from ray_tpu._private.memledger import _pid_alive

        alive: dict[int, bool] = {}
        for e in entries:
            pid = e["creator_pid"]
            if pid not in alive:
                alive[pid] = _pid_alive(pid)
        return {
            "stats": self.backend.stats(),
            "shm_name": getattr(self.backend, "shm_name", None),
            "objects": [{"object_id": e["object_id"].hex(),
                         "size": e["size"], "sealed": e["sealed"],
                         "pins": e["pins"],
                         "pin_pids": pin_pids.get(e["object_id"].hex(),
                                                  []),
                         "creator_pid": e["creator_pid"],
                         "creator_alive": alive[e["creator_pid"]]}
                        for e in entries],
            "truncated": truncated,
            "spilled": [{"object_id": oid.hex(), "path": path,
                         "size": _spill_size(path)}
                        for oid, path in list(self.spilled.items())],
            "spilled_bytes": self.spilled_bytes,
            "pending_deletes": len(self._pending_deletes),
        }

    async def rpc_store_stats(self, h: dict, _b: list) -> dict:
        out = {**self.backend.stats(),
               "spilled_objects": len(self.spilled),
               "spilled_bytes": self.spilled_bytes,
               # Same-host pullers key their direct-shm fast path on
               # this (None for the dict backend).
               "shm_name": getattr(self.backend, "shm_name", None)}
        if h.get("sweep"):
            # Chaos-test hook: reclaim + report pins of crash-killed
            # processes right now (the reaper also does this on a 5s
            # cadence).  0 == nothing was leaked at call time.
            sweep = getattr(self.backend, "sweep_dead", None)
            out["swept_dead_pins"] = int(sweep()) if sweep else 0
        return out

    def close(self) -> None:
        for peer in self._peer_arenas.values():
            try:
                peer.close()
            except Exception:  # noqa: BLE001
                pass
        self._peer_arenas.clear()
        self.backend.close()
        import shutil

        shutil.rmtree(self.spill_dir, ignore_errors=True)
