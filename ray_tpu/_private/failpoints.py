"""Deterministic fault injection at named runtime sites.

The chaos suites (test_chaos.py, test_chaos_adversarial.py) kill
processes at *random* times, so the genuinely hard windows — a crash
between arena alloc and seal, a reply dropped after an actor mutated
state, an agent dying mid-reserve-wave — are hit by luck and never
reproduce on failure.  This module makes those windows addressable: the
runtime compiles `fire("site.name")` calls into each hard window, and a
test (or an operator) arms a site with an action.  The style follows the
`fail` crate / FoundationDB's BUGGIFY and ray's ResourceKillerActor
nightly suites, but sites are *named program points*, not processes.

Syntax (env var or programmatic):

    RAY_TPU_FAILPOINTS="site=action[,site=action...]"

where `action` is a `+`-chained `[modifier+]base`:

    bases:      crash            SIGKILL the process (no cleanup runs)
                error[:ExcName]  raise (default FailpointError; ExcName
                                 resolved from builtins or
                                 ray_tpu.exceptions)
                delay:ms         sleep that many milliseconds in place
                drop             fire() returns True; the site drops the
                                 operation (message/reply/heartbeat)
                off              never fires (counters still advance)
    modifiers:  nth:k            fire on exactly the k-th hit (1-based),
                                 then disarm the site
                prob:p           fire each hit with probability p, from
                                 a per-site seeded RNG
                                 (RAY_TPU_FAILPOINTS_SEED, default 0)

Examples:
    RAY_TPU_FAILPOINTS="arena.copy=crash"
    RAY_TPU_FAILPOINTS="rpc.reply_dispatch=nth:3+drop,agent.heartbeat=prob:0.5+drop"

Cost when disabled: every site is `if failpoints.ACTIVE and
failpoints.fire(...)` — one module-attribute truth test; the function
call only happens while something is armed.

Propagation: `configure()`/`arm()` mirror the table into
``os.environ["RAY_TPU_FAILPOINTS"]``, so worker processes spawned after
arming inherit it (the agent spawns workers with `{**os.environ, ...}`),
and fork()ed children inherit both env and module state (hit counters
reset in the child via `os.register_at_fork`).  Already-running
processes are reached through the `failpoints` RPC verb (`control()`
below), registered on the worker, the node agent (broadcast=True fans
out to its workers), and the controller (broadcast=True fans out to all
agents).
"""
from __future__ import annotations

import logging
import os
import random
import signal
import threading
import time
import zlib

logger = logging.getLogger(__name__)

ENV_VAR = "RAY_TPU_FAILPOINTS"
SEED_VAR = "RAY_TPU_FAILPOINTS_SEED"

# Module flag read by every compiled-in site.  True ONLY while at least
# one site is armed — the disabled-path cost contract.
ACTIVE = False


class FailpointError(RuntimeError):
    """Default exception injected by `error` actions."""


class _Site:
    __slots__ = ("name", "base", "exc_name", "delay_ms", "nth", "prob",
                 "rng", "hits", "fired", "spec")

    def __init__(self, name: str, spec: str, seed: int):
        self.name = name
        self.spec = spec
        self.base = "error"
        self.exc_name = None
        self.delay_ms = 0.0
        self.nth = 0          # 0 = every hit
        self.prob = -1.0      # <0 = unconditional
        self.hits = 0
        self.fired = 0
        # Per-site deterministic stream: same seed + same site + same
        # hit sequence => same decisions, in any process.
        self.rng = random.Random(seed ^ zlib.crc32(name.encode()))
        for part in spec.split("+"):
            part = part.strip()
            if not part:
                continue
            op, _, arg = part.partition(":")
            if op == "nth":
                self.nth = int(arg)
            elif op == "prob":
                self.prob = float(arg)
            elif op == "delay":
                self.base, self.delay_ms = "delay", float(arg)
            elif op == "error":
                self.base, self.exc_name = "error", (arg or None)
            elif op in ("crash", "drop", "off"):
                self.base = op
            else:
                raise ValueError(
                    f"failpoint {name!r}: unknown action part {part!r}")


# site name -> _Site.  Guarded by _lock for mutation AND for the
# multi-item reads in spec()/counters() (fire() on another thread can
# disarm a one-shot mid-iteration); fire()'s own single-key get stays
# lockless (GIL-atomic; a racing re-configure swaps the whole dict).
# RLock: spec() is also called from _sync_env_and_flag under the lock.
_sites: dict[str, _Site] = {}
_lock = threading.RLock()


def _resolve_exc(name: str | None):
    if not name:
        return FailpointError
    import builtins

    cls = getattr(builtins, name, None)
    if cls is None:
        try:
            from ray_tpu import exceptions as _exc

            cls = getattr(_exc, name, None)
        except Exception:  # noqa: BLE001 - exceptions module optional here
            cls = None
    if cls is None:
        try:
            from ray_tpu._private import rpc as _rpc

            cls = getattr(_rpc, name, None)
        except Exception:  # noqa: BLE001
            cls = None
    if not (isinstance(cls, type) and issubclass(cls, BaseException)):
        raise ValueError(f"failpoint error class {name!r} not found")
    return cls


def _evaluate(site: str) -> _Site | None:
    """Shared hit/one-shot/probability accounting for fire()/fire_async().
    Returns the site iff its base action should run this hit."""
    s = _sites.get(site)
    if s is None:
        return None
    # Counter read-modify-writes are NOT GIL-atomic: two executor
    # threads (max_concurrency>1 actors) hitting a `nth:k` site
    # concurrently could both observe the k-th hit (fires twice) or
    # lose an update and skip k entirely (never fires).  The lock is
    # an RLock, so _disarm_after_nth re-acquiring is fine; unarmed
    # sites never reach here (dict miss above, behind the ACTIVE flag).
    with _lock:
        if _sites.get(site) is not s:
            return None         # raced a disarm/re-arm: spec changed
        s.hits += 1
        if s.base == "off":
            return None
        if s.nth:
            if s.hits != s.nth:
                return None
            # One-shot: k-th hit fires, then the site disarms itself and
            # scrubs THIS process's env copy.  A crash action can only
            # scrub the dying process — the spawner's armed env would
            # re-arm every replacement (a crash loop); the spawner closes
            # that hole via on_child_sigkill() when it reaps the victim.
            _disarm_after_nth(site)
        if 0.0 <= s.prob < 1.0 and s.rng.random() >= s.prob:
            return None
        s.fired += 1
        return s


def _crash(site: str) -> None:
    logger.warning("failpoint %s: SIGKILL pid %d", site, os.getpid())
    # Hard death, like a real crash: no finally blocks, no atexit,
    # no flushing — the recovery machinery must cope with exactly
    # this.
    os.kill(os.getpid(), signal.SIGKILL)
    time.sleep(60)      # never returns; belt over suspenders


def fire(site: str) -> bool:
    """Evaluate one armed-or-not site.  Returns True when the site's
    action is `drop` and it fired — the call site skips the operation.
    Only called behind the `ACTIVE` module flag; an unarmed site while
    others are armed is a dict miss.  Sites inside coroutines must use
    fire_async() instead — a `delay` here blocks the whole event loop,
    turning "delay this operation" into "stall the process" (only the
    rpc.io_* sites want that semantics, and they run on the IO thread)."""
    s = _evaluate(site)
    if s is None:
        return False
    if s.base == "crash":
        _crash(site)
    if s.base == "delay":
        time.sleep(s.delay_ms / 1e3)
        return False
    if s.base == "drop":
        logger.warning("failpoint %s: dropping operation", site)
        return True
    raise _resolve_exc(s.exc_name)(f"injected by failpoint {site!r}")


async def fire_async(site: str) -> bool:
    """fire() for sites compiled into coroutines: a `delay` action
    suspends only the operation that hit the site (asyncio.sleep), not
    the whole event loop — e.g. `controller.reserve_wave=delay:5000`
    slows that reserve wave while heartbeats and other RPCs keep
    flowing.  crash/error/drop semantics are identical to fire()."""
    s = _evaluate(site)
    if s is None:
        return False
    if s.base == "crash":
        _crash(site)
    if s.base == "delay":
        import asyncio

        await asyncio.sleep(s.delay_ms / 1e3)
        return False
    if s.base == "drop":
        logger.warning("failpoint %s: dropping operation", site)
        return True
    raise _resolve_exc(s.exc_name)(f"injected by failpoint {site!r}")


def _disarm_after_nth(site: str) -> None:
    with _lock:
        s = _sites.pop(site, None)
        if s is not None:
            # Keep counters visible after the one-shot: tests read them
            # through control() to prove the fault fired.
            _spent[site] = s
        _sync_env_and_flag()


# One-shot sites that already fired (counters survive for inspection).
_spent: dict[str, _Site] = {}


def _sync_env_and_flag() -> None:
    """Mirror the armed table into os.environ (spawn propagation) and
    recompute the ACTIVE flag.  Callers hold _lock."""
    global ACTIVE
    spec_str = spec()
    if spec_str:
        os.environ[ENV_VAR] = spec_str
    else:
        os.environ.pop(ENV_VAR, None)
    ACTIVE = bool(_sites)


def spec() -> str:
    """The armed table as an env-var spec string."""
    with _lock:
        return ",".join(f"{s.name}={s.spec}" for s in _sites.values())


def configure(spec_str: str, seed: int | None = None) -> None:
    """Replace the whole armed table from a spec string (env syntax).
    An empty string disarms everything."""
    if seed is None:
        seed = int(os.environ.get(SEED_VAR, "0") or "0")
    new: dict[str, _Site] = {}
    for pair in (spec_str or "").split(","):
        pair = pair.strip()
        if not pair:
            continue
        site, sep, action = pair.partition("=")
        if not sep:
            raise ValueError(f"failpoint spec {pair!r}: expected "
                             f"site=action")
        new[site.strip()] = _Site(site.strip(), action.strip(), seed)
    with _lock:
        global _sites
        _sites = new
        _spent.clear()
        # Mirror the seed too: spawned children re-parse the spec from
        # env, and a prob: site rebuilt under a different seed would
        # fire on a different schedule than the process that armed it.
        if new:
            os.environ[SEED_VAR] = str(seed)
        else:
            os.environ.pop(SEED_VAR, None)
        _sync_env_and_flag()
    if new:
        logger.info("failpoints armed: %s (seed=%d)", spec_str, seed)


def arm(site: str, action: str, seed: int | None = None) -> None:
    """Arm (or re-arm) one site without touching the others."""
    if seed is None:
        seed = int(os.environ.get(SEED_VAR, "0") or "0")
    with _lock:
        _sites[site] = _Site(site, action, seed)
        _spent.pop(site, None)
        os.environ[SEED_VAR] = str(seed)
        _sync_env_and_flag()


def disarm(site: str) -> None:
    with _lock:
        _sites.pop(site, None)
        _spent.pop(site, None)
        _sync_env_and_flag()


def reset() -> None:
    """Disarm everything and clear counters."""
    configure("")


def reload_from_env() -> None:
    """Re-sync the armed table from os.environ.  Needed by bootstrap
    paths that APPLY env after import — the zygote pre-imports this
    module, then forks and `os.environ.update()`s the worker's env, so
    the import-time arming above never saw it."""
    try:
        configure(os.environ.get(ENV_VAR, ""))
    except Exception:  # noqa: BLE001 - a typo must not kill the worker
        logger.exception("ignoring malformed %s=%r", ENV_VAR,
                         os.environ.get(ENV_VAR))


def on_child_sigkill() -> None:
    """A child of THIS process died by SIGKILL while one-shot (`nth`)
    crash sites are armed here: presume the child just fired one.  The
    dying process scrubbed its OWN env, but this process — whose env the
    replacement will inherit — still has the site armed, so without this
    hook every replacement would crash at ITS k-th hit too, turning
    "fire exactly once" into a crash loop.  Called by the node agent's
    reaper on a -SIGKILL worker exit.  Recurring crash sites (plain
    `crash`, `prob:p+crash`) are intentionally left armed — crashing
    every process at the site is their contract."""
    if not ACTIVE:
        return
    with _lock:
        # agent./controller.-scoped sites can only fire in THIS process,
        # never in a worker child — scrubbing them here would silently
        # cancel an agent-side crash that hasn't happened yet.
        doomed = [n for n, s in _sites.items()
                  if s.base == "crash" and s.nth
                  and not n.startswith(("agent.", "controller."))]
        if not doomed:
            return
        for n in doomed:
            # Counters stay as-is: the fire happened in the CHILD's
            # process, not here — only the arming is scrubbed.
            _spent[n] = _sites.pop(n)
            logger.warning(
                "failpoint %s: disarmed after a child died by SIGKILL "
                "(one-shot crash presumed fired in the child)", n)
        _sync_env_and_flag()


def counters() -> dict:
    """Per-site {hits, fired} — one-shot sites that already fired are
    included (tests assert the fault actually happened)."""
    out = {}
    with _lock:
        for table in (_sites, _spent):
            for name, s in table.items():
                out[name] = {"hits": s.hits, "fired": s.fired,
                             "action": s.spec}
    return out


def control(h: dict) -> dict:
    """The `failpoints` RPC verb body, shared by worker/agent/controller
    handlers.  ops: set (replace table from h["spec"]), arm (one site),
    clear, counters (read-only)."""
    op = h.get("op", "set")
    if op == "set":
        configure(h.get("spec", ""), seed=h.get("seed"))
    elif op == "arm":
        arm(h["site"], h["action"], seed=h.get("seed"))
    elif op == "clear":
        reset()
    elif op != "counters":
        raise ValueError(f"failpoints verb: unknown op {op!r}")
    return {"armed": spec(), "counters": counters(), "pid": os.getpid()}


def _after_fork_child() -> None:
    # Armed state propagates into the child (that is the point); the
    # counters are per-process accounting and restart at zero.
    for table in (_sites, _spent):
        for s in table.values():
            s.hits = 0
            s.fired = 0


os.register_at_fork(after_in_child=_after_fork_child)

# Arm from the environment at import: spawned workers/agents inherit the
# parent's armed table with zero plumbing.
if os.environ.get(ENV_VAR):
    try:
        configure(os.environ[ENV_VAR])
    except Exception:  # noqa: BLE001 - a typo must not kill the runtime
        logger.exception("ignoring malformed %s=%r", ENV_VAR,
                         os.environ.get(ENV_VAR))
