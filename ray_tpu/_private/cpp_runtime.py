"""Worker-side C++ task execution (ray analog: the C++ worker's task
execution loop, cpp/src/ray/runtime/task/task_executor.cc).

A C++ driver submits `cpp_task(lib_path, fn_name, payload)`; the worker
dlopens the user's shared library ONCE (its RAYTPU_REMOTE static
registrars populate the in-library registry) and calls the named function
through the raytpu_cpp_invoke ABI.  The user's compute runs native — the
interpreter only moves the byte buffers.
"""
from __future__ import annotations

import ctypes
import os
import sysconfig

import ray_tpu

_libs: dict[str, ctypes.CDLL] = {}

_NATIVE_DIR = os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..", "native"))
CAPI_SO = os.path.join(_NATIVE_DIR, "build", "libraytpu_capi.so")
CAPI_SRC = os.path.join(_NATIVE_DIR, "capi.cc")
CAPI_HEADER = os.path.join(_NATIVE_DIR, "raytpu_api.h")


def capi_lib_path() -> str:
    """Build (shared mtime-gated flock'd recipe) and return the C ABI
    library path."""
    from ray_tpu._private.native_store import build_native_lib

    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = sysconfig.get_config_var("LDVERSION")
    return build_native_lib(
        CAPI_SRC, CAPI_SO,
        [f"-I{inc}", f"-L{libdir}", f"-Wl,-rpath,{libdir}",
         f"-lpython{pyver}", "-ldl", "-lpthread"])


def _load(lib_path: str) -> ctypes.CDLL:
    lib = _libs.get(lib_path)
    if lib is None:
        if not os.path.exists(lib_path):
            raise FileNotFoundError(
                f"C++ task library not found on this node: {lib_path} "
                "(ship it via runtime_env working_dir or a shared mount)")
        # RTLD_GLOBAL so the user lib's dependency on libraytpu_capi.so
        # shares one registry with any other user lib in this worker.
        lib = ctypes.CDLL(lib_path, mode=ctypes.RTLD_GLOBAL)
        lib.raytpu_cpp_invoke.restype = ctypes.c_int
        lib.raytpu_cpp_invoke.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64)]
        lib.raytpu_last_error.restype = ctypes.c_char_p
        lib.raytpu_buf_free.argtypes = [ctypes.c_void_p]
        lib.raytpu_cpp_actor_new.restype = ctypes.c_uint64
        lib.raytpu_cpp_actor_new.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.raytpu_cpp_actor_invoke.restype = ctypes.c_int
        lib.raytpu_cpp_actor_invoke.argtypes = [
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64)]
        lib.raytpu_cpp_actor_del.argtypes = [ctypes.c_uint64,
                                             ctypes.c_char_p]
        _libs[lib_path] = lib
    return lib


def invoke_native(lib_path: str, fn_name: str, payload: bytes) -> bytes:
    lib = _load(lib_path)
    out = ctypes.c_void_p()
    out_len = ctypes.c_uint64()
    rc = lib.raytpu_cpp_invoke(fn_name.encode(), payload,
                               len(payload), ctypes.byref(out),
                               ctypes.byref(out_len))
    if rc != 0:
        raise RuntimeError(
            f"C++ task {fn_name!r} failed: "
            f"{lib.raytpu_last_error().decode(errors='replace')}")
    try:
        return ctypes.string_at(out, out_len.value)
    finally:
        lib.raytpu_buf_free(out)


@ray_tpu.remote
def cpp_task(lib_path: str, fn_name: str, payload: bytes) -> bytes:
    return invoke_native(lib_path, fn_name, payload)


@ray_tpu.remote
class CppActor:
    """Hosts one native actor instance (ray analog: the C++ worker's
    actor-instance table).  State lives behind a raw pointer inside this
    worker; methods route through raytpu_cpp_actor_invoke.  The ordered
    actor queue gives C++ methods the same one-at-a-time semantics
    Python actors have."""

    def __init__(self, lib_path: str, type_name: str, payload: bytes):
        self._lib = _load(lib_path)
        self._type = type_name.encode()
        self._handle = self._lib.raytpu_cpp_actor_new(
            self._type, payload, len(payload))
        if not self._handle:
            raise RuntimeError(
                f"C++ actor {type_name!r} construction failed: "
                f"{self._lib.raytpu_last_error().decode(errors='replace')}")

    def call(self, method: str, payload: bytes) -> bytes:
        out = ctypes.c_void_p()
        out_len = ctypes.c_uint64()
        rc = self._lib.raytpu_cpp_actor_invoke(
            self._handle, self._type, method.encode(), payload,
            len(payload), ctypes.byref(out), ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError(
                f"C++ actor method {method!r} failed: "
                f"{self._lib.raytpu_last_error().decode(errors='replace')}")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.raytpu_buf_free(out)

    def __del__(self):
        if getattr(self, "_handle", 0):
            try:
                self._lib.raytpu_cpp_actor_del(self._handle, self._type)
            except Exception:  # noqa: BLE001 - interpreter teardown
                pass
            self._handle = 0
