"""Runtime environments: a plugin registry of env kinds.

Analog of ray: python/ray/_private/runtime_env/plugin.py (every env kind
— working_dir, py_modules, pip, conda, containers — is a plugin with
create/modify-context hooks and a per-node URI cache) and
python/ray/runtime_env/runtime_env.py (the user API).

Each kind is a `RuntimeEnvPlugin`:
  - `prepare(value, core)`  driver-side: upload/validate, return the
    msgpack-able wire value carried in task/actor headers;
  - `fetch(wire, core)`     worker-side, BLOCKING, called off the event
    loop (prefetch): download/build into the node-local cache;
  - `activate/deactivate(wire, core, ctx)` around execution: reversible
    (workers are pooled — the reference instead keys dedicated workers
    by runtime env, worker_pool.h:159; reversible activation keeps pool
    reuse with the same isolation semantics).

Built-ins: env_vars, working_dir, py_modules (content-addressed zips in
the controller KV), pip (OFFLINE: `pip install --no-index --find-links
<wheel_dir> --target <hash-dir>`, built once per node under flock), and
venv — the conda analog: a per-hash ISOLATED INTERPRETER
(`python -m venv --system-site-packages` + offline wheels) that the
node agent spawns dedicated workers with (see _ensure_venv).  conda
itself and containers stay absent — this environment has neither a
conda installation nor a container runtime; venv covers the isolated-
interpreter semantics and the plugin seam is where the rest would land.

Custom kinds ship BY VALUE: `runtime_env={"plugins": [MyPlugin(...)]}`
cloudpickles the instances into the descriptor, so a plugin defined in
the driver program works without any worker-side registration.
"""
from __future__ import annotations

import contextlib
import hashlib
import io
import json
import os
import sys
import zipfile

_EXTRACT_ROOT = "/tmp/ray_tpu_runtime_envs"
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 100 * 1024 * 1024


# ------------------------------------------------------------- plugin API
class RuntimeEnvPlugin:
    """One environment kind (ray: runtime_env/plugin.py RuntimeEnvPlugin).

    `name` is the runtime_env dict key the plugin owns; `priority` orders
    activation (lower first — code paths before env vars, like the
    reference's plugin priorities)."""

    name: str = ""
    priority: int = 10

    def prepare(self, value, core):
        """Driver-side: validate/upload; return the wire value."""
        return value

    def fetch(self, wire, core) -> None:
        """Worker-side blocking build/download (off the event loop)."""

    def activate(self, wire, core, ctx: dict) -> None:
        """Set up around execution; stash undo state in ctx."""

    def deactivate(self, wire, core, ctx: dict) -> None:
        """Undo activate (pooled workers must come back clean)."""


class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 20         # after code paths: values may reference them

    def prepare(self, value, core):
        return {str(k): str(v) for k, v in (value or {}).items()}

    def activate(self, wire, core, ctx: dict) -> None:
        saved: dict[str, str | None] = {}
        for k, v in (wire or {}).items():
            saved[k] = os.environ.get(k)
            os.environ[k] = v
        ctx["saved_env"] = saved

    def deactivate(self, wire, core, ctx: dict) -> None:
        for k, old in ctx.get("saved_env", {}).items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _SKIP_DIRS]
            for fname in files:
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                total += os.path.getsize(full)
                if total > MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"runtime_env package exceeds "
                        f"{MAX_PACKAGE_BYTES >> 20}MB: {path}")
                zf.write(full, rel)
    return buf.getvalue()


def _upload_dir(kind: str, path: str, core) -> dict:
    """Content-addressed zip into the controller KV; returns the package
    record (the URI-cache key is the digest — ray: uri_cache.py)."""
    blob = _zip_dir(path)
    digest = hashlib.blake2b(blob, digest_size=16).hexdigest()
    core.call(core.controller_addr, "kv_put",
              {"ns": "pkg", "key": digest}, [blob], timeout=120.0)
    return {"kind": kind, "digest": digest,
            "name": os.path.basename(os.path.abspath(path))}


def _fetch_package(digest: str, core) -> str:
    """Worker-side: content-addressed fetch + extract (idempotent; ray:
    per-node runtime-env agent cache)."""
    target = os.path.join(_EXTRACT_ROOT, digest)
    marker = os.path.join(target, ".ready")
    if os.path.exists(marker):
        return target
    reply, blobs = core.call(core.controller_addr, "kv_get",
                             {"ns": "pkg", "key": digest}, timeout=120.0)
    if not blobs:
        raise RuntimeError(f"runtime_env package {digest} missing from KV")
    os.makedirs(target, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(bytes(blobs[0]))) as zf:
        zf.extractall(target)
    with open(marker, "w") as f:
        f.write("ok")
    return target


class WorkingDirPlugin(RuntimeEnvPlugin):
    name = "working_dir"
    priority = 5

    def prepare(self, value, core):
        return _upload_dir("working_dir", value, core)

    def fetch(self, wire, core) -> None:
        _fetch_package(wire["digest"], core)

    def activate(self, wire, core, ctx: dict) -> None:
        path = _fetch_package(wire["digest"], core)
        ctx["saved_cwd"] = os.getcwd()
        sys.path.insert(0, path)
        ctx.setdefault("added_paths", []).append(path)
        os.chdir(path)

    def deactivate(self, wire, core, ctx: dict) -> None:
        os.chdir(ctx.get("saved_cwd", os.getcwd()))
        for p in ctx.get("added_paths", ()):
            with contextlib.suppress(ValueError):
                sys.path.remove(p)


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 5

    def prepare(self, value, core):
        return [_upload_dir("py_module", p, core) for p in (value or ())]

    def fetch(self, wire, core) -> None:
        for pkg in wire or ():
            _fetch_package(pkg["digest"], core)

    def activate(self, wire, core, ctx: dict) -> None:
        for pkg in wire or ():
            path = _fetch_package(pkg["digest"], core)
            sys.path.insert(0, path)
            ctx.setdefault("added_paths", []).append(path)

    def deactivate(self, wire, core, ctx: dict) -> None:
        for p in ctx.get("added_paths", ()):
            with contextlib.suppress(ValueError):
                sys.path.remove(p)


def _pip_env_hash(pip_desc: dict) -> str:
    return hashlib.blake2b(
        json.dumps(pip_desc, sort_keys=True).encode(),
        digest_size=16).hexdigest()


def _build_once(kind: str, desc: dict, build_fn) -> str:
    """Node-local build-once per env hash (ray: pip.py _install_pip,
    keyed and locked the same way): fast path on a .ready marker,
    flock + double-check, build into a scratch dir, atomic rename.
    A crash-killed build must never leave a half-copied target that a
    later build would skip over, hence scratch + rename.
    `build_fn(tmp_dir)` populates the scratch dir (and raises on
    failure); returns the target dir."""
    import fcntl
    import shutil

    h = _pip_env_hash(desc)
    target = os.path.join(_EXTRACT_ROOT, kind, h)
    marker = os.path.join(target, ".ready")
    if os.path.exists(marker):
        return target
    os.makedirs(os.path.dirname(target), exist_ok=True)
    with open(target + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):      # built while we waited
                return target
            tmp = target + ".build"
            shutil.rmtree(tmp, ignore_errors=True)
            shutil.rmtree(target, ignore_errors=True)
            try:
                build_fn(tmp)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            with open(os.path.join(tmp, ".ready"), "w") as f:
                f.write("ok")
            os.rename(tmp, target)
            return target
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _pip_install_offline(wheel_dir: str, packages: list, site: str) -> None:
    """`pip install --no-index --find-links <wheel_dir> --target <site>`
    — the only package source in a zero-egress environment."""
    import subprocess

    cmd = [sys.executable, "-m", "pip", "install", "--quiet",
           "--no-index", "--find-links", wheel_dir,
           "--target", site, *packages]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"runtime_env pip build failed: {proc.stderr[-2000:]}")


def _ensure_pip_env(pip_desc: dict) -> str:
    return _build_once(
        "pip", pip_desc,
        lambda tmp: _pip_install_offline(
            pip_desc["wheel_dir"], pip_desc["packages"], tmp))


class PipPlugin(RuntimeEnvPlugin):
    name = "pip"
    priority = 8          # before env_vars, after code dirs

    def prepare(self, value, core):
        if isinstance(value, dict):
            reqs = sorted(value.get("packages", ()))
            wheel_dir = value.get("wheel_dir")
        else:
            reqs = sorted(value)
            wheel_dir = None
        wheel_dir = wheel_dir or os.environ.get("RAY_TPU_WHEEL_DIR")
        if not wheel_dir:
            raise ValueError(
                "pip runtime_env needs a local wheel source (no egress): "
                'pass {"pip": {"packages": [...], "wheel_dir": ...}} or '
                "set RAY_TPU_WHEEL_DIR")
        return {"packages": reqs, "wheel_dir": os.path.abspath(wheel_dir)}

    def fetch(self, wire, core) -> None:
        _ensure_pip_env(wire)

    def activate(self, wire, core, ctx: dict) -> None:
        path = _ensure_pip_env(wire)
        sys.path.insert(0, path)
        ctx["pip_path"] = path
        ctx["mods_before"] = set(sys.modules)
        import importlib

        importlib.invalidate_caches()

    def deactivate(self, wire, core, ctx: dict) -> None:
        path = ctx.get("pip_path")
        if path is None:
            return
        with contextlib.suppress(ValueError):
            sys.path.remove(path)
        # Evict modules the pip env provided so the NEXT task in this
        # pooled worker doesn't see them.
        for name in list(set(sys.modules) - ctx.get("mods_before", set())):
            mod = sys.modules.get(name)
            origin = getattr(mod, "__file__", "") or ""
            if origin.startswith(path):
                del sys.modules[name]
        import importlib

        importlib.invalidate_caches()


def _ensure_venv(desc: dict) -> str:
    """Node-local ISOLATED INTERPRETER per env hash — the conda analog
    (ray: runtime_env/conda.py building a dedicated env and running the
    worker with its python).  `python -m venv --system-site-packages`
    (jax/torch stay importable), offline wheels installed into its
    site-packages, built once per node via _build_once.  Returns the
    venv's python executable; the node agent spawns a DEDICATED worker
    with it (workers are keyed by env, like the reference's
    runtime-env-keyed WorkerPool, worker_pool.h:159) — in-process
    activation cannot swap interpreters, so this kind is the one that
    routes through spawn."""

    def build(tmp: str) -> None:
        import venv as venv_mod

        venv_mod.create(tmp, system_site_packages=True,
                        with_pip=False, symlinks=True)
        site = os.path.join(
            tmp, "lib",
            f"python{sys.version_info.major}.{sys.version_info.minor}",
            "site-packages")
        if desc.get("packages"):
            _pip_install_offline(desc["wheel_dir"], desc["packages"], site)
        # Make ray_tpu resolvable from the venv interpreter via a .pth
        # (appended AFTER the venv's own site-packages, so env packages
        # SHADOW the agent's — a PYTHONPATH entry would invert that and
        # defeat version isolation).  Covers the repo-checkout case;
        # a pip-installed ray_tpu is already visible via system site.
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        pkg_parent = os.path.dirname(pkg_parent)   # ray_tpu/ -> its parent
        with open(os.path.join(site, "ray_tpu_agent_path.pth"), "w") as f:
            f.write(pkg_parent + "\n")

    target = _build_once("venv", desc, build)
    return os.path.join(target, "bin", "python")


class VenvPlugin(RuntimeEnvPlugin):
    """Isolated-interpreter env kind (`runtime_env={"venv": {...}}`).

    Unlike every other kind, the env IS the process: tasks/actors with a
    venv env schedule onto dedicated workers the agent spawns with the
    venv's python (lease headers carry the desc; node_agent keys workers
    by its hash).  activate() is therefore a sanity check, not a setup.
    """

    name = "venv"
    priority = 1

    def prepare(self, value, core):
        if value is True or value is None:
            value = {}
        reqs = sorted(value.get("packages", ()))
        wheel_dir = value.get("wheel_dir") \
            or os.environ.get("RAY_TPU_WHEEL_DIR")
        if reqs and not wheel_dir:
            raise ValueError(
                "venv runtime_env with packages needs a local wheel "
                'source (no egress): {"venv": {"packages": [...], '
                '"wheel_dir": ...}} or RAY_TPU_WHEEL_DIR')
        out = {"packages": reqs}
        if wheel_dir:
            out["wheel_dir"] = os.path.abspath(wheel_dir)
        return out

    def fetch(self, wire, core) -> None:
        _ensure_venv(wire)

    def activate(self, wire, core, ctx: dict) -> None:
        # The agent routed this task to a worker ALREADY RUNNING the
        # venv's interpreter; nothing to do but verify we are in it.
        expect = os.path.join(_EXTRACT_ROOT, "venv", _pip_env_hash(wire))
        if not sys.prefix.startswith(expect):
            raise RuntimeError(
                f"venv runtime_env task ran outside its env "
                f"(prefix {sys.prefix}, want {expect}) — agent routing "
                "bug")


def venv_key(desc: dict | None) -> str | None:
    """Worker-pool key for a runtime env descriptor's venv kind (None =
    plain pooled worker).  Used by the submit path (scheduling keys),
    lease headers, and the agent's keyed worker match."""
    if not desc or "venv" not in desc:
        return None
    return _pip_env_hash(desc["venv"])


_BUILTINS: dict[str, RuntimeEnvPlugin] = {
    p.name: p for p in (EnvVarsPlugin(), WorkingDirPlugin(),
                        PyModulesPlugin(), PipPlugin(), VenvPlugin())
}

# Driver-side registry for additional kinds usable by dict key
# (ray: RAY_RUNTIME_ENV_PLUGINS class-path registration).
_registered: dict[str, RuntimeEnvPlugin] = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name or plugin.name in _BUILTINS:
        raise ValueError(f"invalid plugin name {plugin.name!r}")
    _registered[plugin.name] = plugin


class RuntimeEnv(dict):
    """User-facing descriptor (ray: runtime_env/runtime_env.py RuntimeEnv).

    Built-in keys: env_vars (dict), working_dir (path), py_modules (list
    of paths), pip (list of requirements, or {"packages": [...],
    "wheel_dir": path} for offline resolution), venv ({"packages": [...],
    "wheel_dir": path} or True — isolated interpreter, the conda analog).
    `plugins` takes a list of RuntimeEnvPlugin INSTANCES; registered
    plugin names are accepted as extra keys."""

    def __init__(self, env_vars: dict | None = None,
                 working_dir: str | None = None,
                 py_modules: list | None = None,
                 pip: list | dict | None = None,
                 venv: dict | bool | None = None,
                 plugins: list | None = None, **kwargs):
        unknown = set(kwargs) - set(_registered)
        if unknown:
            raise ValueError(
                f"unsupported runtime_env keys {sorted(unknown)}; "
                f"supported: {sorted(set(_BUILTINS) | set(_registered))} "
                "+ plugins=[...]")
        super().__init__()
        if env_vars:
            self["env_vars"] = dict(env_vars)
        if working_dir:
            self["working_dir"] = working_dir
        if py_modules:
            self["py_modules"] = list(py_modules)
        if pip:
            self["pip"] = pip
        if venv:
            self["venv"] = venv
        if plugins:
            self["plugins"] = list(plugins)
        self.update(kwargs)


# ---------------------------------------------------------- entry points
def prepare(runtime_env: dict | None, core) -> dict | None:
    """Driver-side: run every kind's plugin, return the wire descriptor
    (ray: runtime-env URI creation + GCS package upload).  Wire format:
    built-ins keep their own keys ("packages" merges working_dir +
    py_modules for back-compat); instance plugins ride "__plugins__" as
    cloudpickle blobs — defined-in-driver plugins work with no
    worker-side registration."""
    if not runtime_env:
        return None
    desc: dict = {}
    packages: list[dict] = []
    for key, value in runtime_env.items():
        if key == "plugins":
            continue
        plugin = _BUILTINS.get(key) or _registered.get(key)
        if plugin is None:
            raise ValueError(f"unsupported runtime_env key {key!r}")
        wire = plugin.prepare(value, core)
        if key == "working_dir":
            packages.append(wire)
        elif key == "py_modules":
            packages.extend(wire)
        elif key in _BUILTINS:
            if wire:
                desc[key] = wire
        else:
            # Registered-by-name plugin: ship instance + wire by value.
            import cloudpickle

            desc.setdefault("__plugins__", []).append(
                cloudpickle.dumps((plugin, wire)))
    if packages:
        desc["packages"] = packages
    for plugin in runtime_env.get("plugins", ()):
        import cloudpickle

        wire = plugin.prepare(runtime_env.get(plugin.name), core)
        desc.setdefault("__plugins__", []).append(
            cloudpickle.dumps((plugin, wire)))
    return desc or None


def _desc_plugins(desc: dict) -> list[tuple[RuntimeEnvPlugin, object]]:
    """(plugin, wire) pairs for one descriptor, activation-ordered."""
    out: list[tuple[RuntimeEnvPlugin, object]] = []
    for pkg in desc.get("packages", ()):
        plugin = _BUILTINS["working_dir" if pkg["kind"] == "working_dir"
                           else "py_modules"]
        wire = pkg if pkg["kind"] == "working_dir" else [pkg]
        out.append((plugin, wire))
    for key in ("pip", "env_vars"):
        if desc.get(key) is not None:
            out.append((_BUILTINS[key], desc[key]))
    for blob in desc.get("__plugins__", ()):
        import pickle

        plugin, wire = pickle.loads(blob)
        out.append((plugin, wire))
    out.sort(key=lambda pw: pw[0].priority)
    return out


def prefetch(desc: dict | None, core) -> None:
    """Blocking fetch/build of everything in the descriptor.  MUST be
    called off the event loop (run_in_executor) before activating a
    runtime env on the loop thread (async actors): fetches block on
    controller RPCs and pip builds run subprocesses."""
    for plugin, wire in _desc_plugins(desc or {}):
        plugin.fetch(wire, core)


@contextlib.contextmanager
def activate(desc: dict | None, core):
    """Worker-side activation around execution, reversible in LIFO order
    (workers are pooled)."""
    if not desc:
        yield
        return
    done: list[tuple[RuntimeEnvPlugin, object, dict]] = []
    try:
        for plugin, wire in _desc_plugins(desc):
            ctx: dict = {}
            plugin.activate(wire, core, ctx)
            done.append((plugin, wire, ctx))
        yield
    finally:
        for plugin, wire, ctx in reversed(done):
            try:
                plugin.deactivate(wire, core, ctx)
            except Exception:  # noqa: BLE001 - teardown best-effort
                import logging

                logging.getLogger(__name__).exception(
                    "runtime_env deactivate failed for %s", plugin.name)
