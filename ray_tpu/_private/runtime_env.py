"""Runtime environments: per-task/actor env_vars, code shipping, pip envs.

Analog of ray: python/ray/_private/runtime_env/ (working_dir.py,
py_modules.py, pip.py; provisioning agent under runtime_env/agent/) and
python/ray/runtime_env/runtime_env.py (the user API).  Collapsed for this
runtime: the driver packages working_dir / py_modules into a
content-addressed zip in the controller KV; workers fetch + extract once
per digest and activate (sys.path + cwd + env vars) around execution.

pip envs are OFFLINE-capable (this machine has no egress): packages
resolve from a local wheel directory via `pip install --no-index
--find-links <wheel_dir> --target <env>` into a per-hash site directory,
built once per node under a file lock and cached (ray: pip.py builds a
per-hash virtualenv; the --target site-dir is the no-network equivalent
— activation prepends it to sys.path and deactivation evicts the modules
it provided, so pooled workers stay reusable).
"""
from __future__ import annotations

import contextlib
import hashlib
import io
import json
import os
import sys
import zipfile

_EXTRACT_ROOT = "/tmp/ray_tpu_runtime_envs"
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 100 * 1024 * 1024


class RuntimeEnv(dict):
    """User-facing descriptor (ray: runtime_env/runtime_env.py RuntimeEnv).

    Supported keys: env_vars (dict), working_dir (path), py_modules
    (list of paths), pip (list of requirements, or
    {"packages": [...], "wheel_dir": path} for offline resolution).
    """

    _KEYS = {"env_vars", "working_dir", "py_modules", "pip"}

    def __init__(self, env_vars: dict | None = None,
                 working_dir: str | None = None,
                 py_modules: list | None = None,
                 pip: list | dict | None = None, **kwargs):
        unknown = set(kwargs) - self._KEYS
        if unknown:
            raise ValueError(
                f"unsupported runtime_env keys {sorted(unknown)}; "
                f"supported: {sorted(self._KEYS)}")
        super().__init__()
        if env_vars:
            self["env_vars"] = dict(env_vars)
        if working_dir:
            self["working_dir"] = working_dir
        if py_modules:
            self["py_modules"] = list(py_modules)
        if pip:
            self["pip"] = pip
        self.update(kwargs)


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _SKIP_DIRS]
            for fname in files:
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                total += os.path.getsize(full)
                if total > MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"runtime_env package exceeds "
                        f"{MAX_PACKAGE_BYTES >> 20}MB: {path}")
                zf.write(full, rel)
    return buf.getvalue()


def prepare(runtime_env: dict | None, core) -> dict | None:
    """Driver-side: upload code packages, return the wire descriptor
    (ray: runtime-env URI creation + GCS package upload)."""
    if not runtime_env:
        return None
    desc: dict = {}
    if runtime_env.get("env_vars"):
        desc["env_vars"] = {str(k): str(v)
                            for k, v in runtime_env["env_vars"].items()}
    packages = []
    paths = []
    if runtime_env.get("working_dir"):
        paths.append(("working_dir", runtime_env["working_dir"]))
    for p in runtime_env.get("py_modules", ()):
        paths.append(("py_module", p))
    for kind, p in paths:
        blob = _zip_dir(p)
        digest = hashlib.blake2b(blob, digest_size=16).hexdigest()
        core.call(core.controller_addr, "kv_put",
                  {"ns": "pkg", "key": digest}, [blob], timeout=120.0)
        packages.append({"kind": kind, "digest": digest,
                         "name": os.path.basename(os.path.abspath(p))})
    if packages:
        desc["packages"] = packages
    pip_spec = runtime_env.get("pip")
    if pip_spec:
        if isinstance(pip_spec, dict):
            reqs = sorted(pip_spec.get("packages", ()))
            wheel_dir = pip_spec.get("wheel_dir")
        else:
            reqs = sorted(pip_spec)
            wheel_dir = None
        wheel_dir = wheel_dir or os.environ.get("RAY_TPU_WHEEL_DIR")
        if not wheel_dir:
            raise ValueError(
                "pip runtime_env needs a local wheel source (no egress): "
                'pass {"pip": {"packages": [...], "wheel_dir": ...}} or '
                "set RAY_TPU_WHEEL_DIR")
        desc["pip"] = {"packages": reqs,
                       "wheel_dir": os.path.abspath(wheel_dir)}
    return desc or None


def _pip_env_hash(pip_desc: dict) -> str:
    return hashlib.blake2b(
        json.dumps(pip_desc, sort_keys=True).encode(),
        digest_size=16).hexdigest()


def _ensure_pip_env(pip_desc: dict) -> str:
    """Node-local build-once per env hash (ray: pip.py _install_pip
    building the per-hash virtualenv, keyed and locked the same way).
    Offline: --no-index --find-links only."""
    import fcntl
    import subprocess

    h = _pip_env_hash(pip_desc)
    target = os.path.join(_EXTRACT_ROOT, "pip", h)
    marker = os.path.join(target, ".ready")
    if os.path.exists(marker):
        return target
    os.makedirs(os.path.dirname(target), exist_ok=True)
    lock_path = target + ".lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):      # built while we waited
                return target
            # Build into a scratch dir + atomic rename: a crash-killed
            # build must never leave a half-copied target that a later
            # `pip install --target` would skip over (pip refuses to
            # replace an existing dir without --upgrade).
            import shutil

            tmp = target + ".build"
            shutil.rmtree(tmp, ignore_errors=True)
            shutil.rmtree(target, ignore_errors=True)
            cmd = [sys.executable, "-m", "pip", "install", "--quiet",
                   "--no-index", "--find-links", pip_desc["wheel_dir"],
                   "--target", tmp, *pip_desc["packages"]]
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=600)
            if proc.returncode != 0:
                shutil.rmtree(tmp, ignore_errors=True)
                raise RuntimeError(
                    f"pip runtime_env build failed: {proc.stderr[-2000:]}")
            with open(os.path.join(tmp, ".ready"), "w") as f:
                f.write("ok")
            os.rename(tmp, target)
            return target
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _fetch_package(digest: str, core) -> str:
    """Worker-side: content-addressed fetch + extract (idempotent; ray:
    per-node runtime-env agent cache)."""
    target = os.path.join(_EXTRACT_ROOT, digest)
    marker = os.path.join(target, ".ready")
    if os.path.exists(marker):
        return target
    reply, blobs = core.call(core.controller_addr, "kv_get",
                             {"ns": "pkg", "key": digest}, timeout=120.0)
    if not blobs:
        raise RuntimeError(f"runtime_env package {digest} missing from KV")
    os.makedirs(target, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(bytes(blobs[0]))) as zf:
        zf.extractall(target)
    with open(marker, "w") as f:
        f.write("ok")
    return target


def prefetch(desc: dict | None, core) -> None:
    """Blocking fetch/build of everything in the descriptor.  MUST be
    called off the event loop (run_in_executor) before activating a
    runtime env on the loop thread (async actors): _fetch_package's
    core.call blocks on the loop, so calling it from the loop deadlocks
    the worker.  (pip builds also run subprocesses — same rule.)"""
    for pkg in (desc or {}).get("packages", ()):
        _fetch_package(pkg["digest"], core)
    if (desc or {}).get("pip"):
        _ensure_pip_env(desc["pip"])


@contextlib.contextmanager
def activate(desc: dict | None, core):
    """Worker-side activation around execution: env vars set/restored,
    packages on sys.path (working_dir also becomes cwd).  Worker processes
    are pooled, so activation must be reversible (the reference instead
    dedicates workers per runtime env — worker_pool.h:159 runtime-env-keyed
    pooling; that isolation level is a TODO here)."""
    if not desc:
        yield
        return
    saved_env: dict[str, str | None] = {}
    added_paths: list[str] = []
    saved_cwd = os.getcwd()
    pip_path: str | None = None
    mods_before: set[str] | None = None
    try:
        for k, v in (desc.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        for pkg in desc.get("packages", ()):
            path = _fetch_package(pkg["digest"], core)
            sys.path.insert(0, path)
            added_paths.append(path)
            if pkg["kind"] == "working_dir":
                os.chdir(path)
        if desc.get("pip"):
            pip_path = _ensure_pip_env(desc["pip"])
            sys.path.insert(0, pip_path)
            added_paths.append(pip_path)
            mods_before = set(sys.modules)
            import importlib

            importlib.invalidate_caches()
        yield
    finally:
        os.chdir(saved_cwd)
        for p in added_paths:
            with contextlib.suppress(ValueError):
                sys.path.remove(p)
        if pip_path is not None and mods_before is not None:
            # Evict modules the pip env provided so the NEXT task in this
            # pooled worker doesn't see them (the reference instead keys
            # dedicated workers by runtime env — worker_pool.h:159; this
            # keeps pool reuse while preserving the isolation semantics).
            for name in list(set(sys.modules) - mods_before):
                mod = sys.modules.get(name)
                origin = getattr(mod, "__file__", "") or ""
                if origin.startswith(pip_path):
                    del sys.modules[name]
            import importlib

            importlib.invalidate_caches()
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
