"""Runtime environments: per-task/actor env_vars + code shipping.

Analog of ray: python/ray/_private/runtime_env/ (working_dir.py,
py_modules.py, plugin architecture; provisioning agent under
runtime_env/agent/) and python/ray/runtime_env/runtime_env.py (the user
API).  Collapsed for this runtime: the driver packages working_dir /
py_modules into a content-addressed zip in the controller KV; workers
fetch + extract once per digest and activate (sys.path + cwd + env vars)
around execution.  Conda/pip provisioning is intentionally out of scope
in this environment (no installs) — a plugin can add it via the same
descriptor mechanism.
"""
from __future__ import annotations

import contextlib
import hashlib
import io
import os
import sys
import zipfile

_EXTRACT_ROOT = "/tmp/ray_tpu_runtime_envs"
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 100 * 1024 * 1024


class RuntimeEnv(dict):
    """User-facing descriptor (ray: runtime_env/runtime_env.py RuntimeEnv).

    Supported keys: env_vars (dict), working_dir (path), py_modules
    (list of paths).
    """

    _KEYS = {"env_vars", "working_dir", "py_modules"}

    def __init__(self, env_vars: dict | None = None,
                 working_dir: str | None = None,
                 py_modules: list | None = None, **kwargs):
        unknown = set(kwargs) - self._KEYS
        if unknown:
            raise ValueError(
                f"unsupported runtime_env keys {sorted(unknown)}; "
                f"supported: {sorted(self._KEYS)}")
        super().__init__()
        if env_vars:
            self["env_vars"] = dict(env_vars)
        if working_dir:
            self["working_dir"] = working_dir
        if py_modules:
            self["py_modules"] = list(py_modules)
        self.update(kwargs)


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _SKIP_DIRS]
            for fname in files:
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                total += os.path.getsize(full)
                if total > MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"runtime_env package exceeds "
                        f"{MAX_PACKAGE_BYTES >> 20}MB: {path}")
                zf.write(full, rel)
    return buf.getvalue()


def prepare(runtime_env: dict | None, core) -> dict | None:
    """Driver-side: upload code packages, return the wire descriptor
    (ray: runtime-env URI creation + GCS package upload)."""
    if not runtime_env:
        return None
    desc: dict = {}
    if runtime_env.get("env_vars"):
        desc["env_vars"] = {str(k): str(v)
                            for k, v in runtime_env["env_vars"].items()}
    packages = []
    paths = []
    if runtime_env.get("working_dir"):
        paths.append(("working_dir", runtime_env["working_dir"]))
    for p in runtime_env.get("py_modules", ()):
        paths.append(("py_module", p))
    for kind, p in paths:
        blob = _zip_dir(p)
        digest = hashlib.blake2b(blob, digest_size=16).hexdigest()
        core.call(core.controller_addr, "kv_put",
                  {"ns": "pkg", "key": digest}, [blob], timeout=120.0)
        packages.append({"kind": kind, "digest": digest,
                         "name": os.path.basename(os.path.abspath(p))})
    if packages:
        desc["packages"] = packages
    return desc or None


def _fetch_package(digest: str, core) -> str:
    """Worker-side: content-addressed fetch + extract (idempotent; ray:
    per-node runtime-env agent cache)."""
    target = os.path.join(_EXTRACT_ROOT, digest)
    marker = os.path.join(target, ".ready")
    if os.path.exists(marker):
        return target
    reply, blobs = core.call(core.controller_addr, "kv_get",
                             {"ns": "pkg", "key": digest}, timeout=120.0)
    if not blobs:
        raise RuntimeError(f"runtime_env package {digest} missing from KV")
    os.makedirs(target, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(bytes(blobs[0]))) as zf:
        zf.extractall(target)
    with open(marker, "w") as f:
        f.write("ok")
    return target


def prefetch(desc: dict | None, core) -> None:
    """Blocking fetch of every package in the descriptor.  MUST be called
    off the event loop (run_in_executor) before activating a runtime env
    on the loop thread (async actors): _fetch_package's core.call blocks
    on the loop, so calling it from the loop deadlocks the worker."""
    for pkg in (desc or {}).get("packages", ()):
        _fetch_package(pkg["digest"], core)


@contextlib.contextmanager
def activate(desc: dict | None, core):
    """Worker-side activation around execution: env vars set/restored,
    packages on sys.path (working_dir also becomes cwd).  Worker processes
    are pooled, so activation must be reversible (the reference instead
    dedicates workers per runtime env — worker_pool.h:159 runtime-env-keyed
    pooling; that isolation level is a TODO here)."""
    if not desc:
        yield
        return
    saved_env: dict[str, str | None] = {}
    added_paths: list[str] = []
    saved_cwd = os.getcwd()
    try:
        for k, v in (desc.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        for pkg in desc.get("packages", ()):
            path = _fetch_package(pkg["digest"], core)
            sys.path.insert(0, path)
            added_paths.append(path)
            if pkg["kind"] == "working_dir":
                os.chdir(path)
        yield
    finally:
        os.chdir(saved_cwd)
        for p in added_paths:
            with contextlib.suppress(ValueError):
                sys.path.remove(p)
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
