"""User-facing profiling spans recorded into the task-event timeline.

Analog of ray: python/ray/_private/profiling.py (`profiling.profile`
context) — spans land in the same controller-side event buffer the task
state transitions use (ray: TaskEventBuffer task_event_buffer.h:206), so
`ray_tpu.timeline()` / the CLI's Chrome-trace export interleaves them
with task lifecycle events.
"""
from __future__ import annotations

import time
from contextlib import contextmanager

# Stamp order of one traced sync call, caller-side entry to caller-side
# return.  Not every stamp appears on every path: the fused fast path
# stamps caller_post/caller_wake (no event loop on the caller's critical
# path), the loop path stamps loop_call/caller_loop_wake instead.
HOP_ORDER = (
    "caller_entry",       # API entry on the caller thread (arm time)
    "caller_post",        # fused path: posted straight to the IO thread
    "loop_call",          # loop path: RpcClient.call ran on the loop
    "io_send",            # IO thread handed the frames to zmq
    "peer_recv",          # executee IO thread pulled the frames off zmq
    "peer_dispatch",      # executee loop picked the request up
    "exec_start",         # executor thread entered user code
    "exec_end",           # executor thread left user code
    "handler_done",       # executee loop finished the handler
    "reply_io_send",      # executee IO thread sent the reply
    "reply_recv",         # caller IO thread received the reply
    "caller_loop_wake",   # loop path: reply future resolved on the loop
    "caller_wake",        # fused path: blocked caller thread released
    "caller_done",        # API returned on the caller thread
)


def arm_hop_trace(methods: tuple = ("actor_call",)) -> None:
    """Trace the next outgoing RPC whose method matches (one-shot).
    See `hop_trace` for the usual usage."""
    from ray_tpu._private import rpc

    rpc.arm_hop_trace(methods)


def last_hop_trace() -> dict | None:
    """Raw stamps (name -> monotonic seconds) of the most recent traced
    call, cleared on read."""
    from ray_tpu._private import rpc

    return rpc.take_hop_trace()


@contextmanager
def hop_trace(methods: tuple = ("actor_call",)):
    """Trace ONE sync call's per-hop latency:

        with profiling.hop_trace() as rec:
            ray_tpu.get(counter.inc.remote())
        table = profiling.hop_breakdown_us(rec)

    The yielded dict gains "hops" (raw stamps) and "caller_done" when the
    block exits; feed it to `hop_breakdown_us` for per-hop microseconds."""
    from ray_tpu._private import rpc

    rec: dict = {}
    rpc.arm_hop_trace(methods)
    try:
        yield rec
    finally:
        rec["caller_done"] = time.monotonic()
        rec["hops"] = rpc.take_hop_trace()
        rpc.disarm_hop_trace()


def hop_breakdown_us(rec: dict) -> dict:
    """Per-hop latency table (microseconds between consecutive observed
    stamps, in HOP_ORDER) for a completed `hop_trace` record.  Empty when
    the traced call never fired (e.g. the value resolved locally)."""
    hops = dict(rec.get("hops") or {})
    if not hops:
        return {}
    if "caller_done" in rec:
        hops["caller_done"] = rec["caller_done"]
    present = [(k, hops[k]) for k in HOP_ORDER if k in hops]
    if len(present) < 2:
        return {}
    out: dict = {}
    prev_name, prev_t = present[0]
    for name, t in present[1:]:
        out[f"{prev_name}->{name}_us"] = round((t - prev_t) * 1e6, 1)
        prev_name, prev_t = name, t
    out["total_us"] = round((present[-1][1] - present[0][1]) * 1e6, 1)
    return out


@contextmanager
def profile(event_name: str, extra_data: dict | None = None):
    """Record a named span attributed to the current task (or the driver).

    with ray_tpu.profiling.profile("shuffle-partition"):
        ...
    """
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    owner = core.current_task_id or "driver"
    name = event_name if not extra_data else \
        f"{event_name} {extra_data}"
    core._record_event(owner, "PROFILE_BEGIN", name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        core._record_event(
            owner, "PROFILE_END",
            f"{name} ({(time.perf_counter() - t0) * 1e3:.2f}ms)")
