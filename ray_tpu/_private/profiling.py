"""User-facing profiling spans recorded into the task-event timeline.

Analog of ray: python/ray/_private/profiling.py (`profiling.profile`
context) — spans land in the same controller-side event buffer the task
state transitions use (ray: TaskEventBuffer task_event_buffer.h:206), so
`ray_tpu.timeline()` / the CLI's Chrome-trace export interleaves them
with task lifecycle events.
"""
from __future__ import annotations

import time
from contextlib import contextmanager


@contextmanager
def profile(event_name: str, extra_data: dict | None = None):
    """Record a named span attributed to the current task (or the driver).

    with ray_tpu.profiling.profile("shuffle-partition"):
        ...
    """
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    owner = core.current_task_id or "driver"
    name = event_name if not extra_data else \
        f"{event_name} {extra_data}"
    core._record_event(owner, "PROFILE_BEGIN", name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        core._record_event(
            owner, "PROFILE_END",
            f"{name} ({(time.perf_counter() - t0) * 1e3:.2f}ms)")
