"""User-facing profiling spans recorded into the task-event timeline.

Analog of ray: python/ray/_private/profiling.py (`profiling.profile`
context) — spans land in the same controller-side event buffer the task
state transitions use (ray: TaskEventBuffer task_event_buffer.h:206), so
`ray_tpu.timeline()` / the CLI's Chrome-trace export interleaves them
with task lifecycle events.
"""
from __future__ import annotations

import time
from contextlib import contextmanager

# Stamp order of one traced sync call, caller-side entry to caller-side
# return.  Not every stamp appears on every path: the fused fast path
# stamps caller_post/caller_wake (no event loop on the caller's critical
# path), the loop path stamps loop_call/caller_loop_wake instead.
HOP_ORDER = (
    "caller_entry",       # API entry on the caller thread (arm time)
    "caller_post",        # fused path: posted straight to the IO thread
    "loop_call",          # loop path: RpcClient.call ran on the loop
    "io_send",            # IO thread handed the frames to zmq
    "peer_recv",          # executee IO thread pulled the frames off zmq
    "peer_dispatch",      # executee loop picked the request up
    "exec_start",         # executor thread entered user code
    "exec_end",           # executor thread left user code
    "handler_done",       # executee loop finished the handler
    "reply_io_send",      # executee IO thread sent the reply
    "reply_recv",         # caller IO thread received the reply
    "caller_loop_wake",   # loop path: reply future resolved on the loop
    "caller_wake",        # fused path: blocked caller thread released
    "caller_done",        # API returned on the caller thread
)


def arm_hop_trace(methods: tuple = ("actor_call",)) -> None:
    """Trace the next outgoing RPC whose method matches (one-shot).
    See `hop_trace` for the usual usage."""
    from ray_tpu._private import rpc

    rpc.arm_hop_trace(methods)


def last_hop_trace() -> dict | None:
    """Raw stamps (name -> monotonic seconds) of the most recent traced
    call, cleared on read."""
    from ray_tpu._private import rpc

    return rpc.take_hop_trace()


@contextmanager
def hop_trace(methods: tuple = ("actor_call",)):
    """Trace ONE sync call's per-hop latency:

        with profiling.hop_trace() as rec:
            ray_tpu.get(counter.inc.remote())
        table = profiling.hop_breakdown_us(rec)

    The yielded dict gains "hops" (raw stamps) and "caller_done" when the
    block exits; feed it to `hop_breakdown_us` for per-hop microseconds."""
    from ray_tpu._private import rpc

    rec: dict = {}
    rpc.arm_hop_trace(methods)
    try:
        yield rec
    finally:
        rec["caller_done"] = time.monotonic()
        rec["hops"] = rpc.take_hop_trace()
        rpc.disarm_hop_trace()


def hop_breakdown_us(rec: dict) -> dict:
    """Per-hop latency table (microseconds between consecutive observed
    stamps, in HOP_ORDER) for a completed `hop_trace` record.  Empty when
    the traced call never fired (e.g. the value resolved locally)."""
    hops = dict(rec.get("hops") or {})
    if not hops:
        return {}
    if "caller_done" in rec:
        hops["caller_done"] = rec["caller_done"]
    present = [(k, hops[k]) for k in HOP_ORDER if k in hops]
    if len(present) < 2:
        return {}
    out: dict = {}
    prev_name, prev_t = present[0]
    for name, t in present[1:]:
        out[f"{prev_name}->{name}_us"] = round((t - prev_t) * 1e6, 1)
        prev_name, prev_t = name, t
    out["total_us"] = round((present[-1][1] - present[0][1]) * 1e6, 1)
    return out


# ------------------------------------------------------------ put tracer
# Stamp order of one traced `ray_tpu.put`, API entry to API return.  The
# arena path stamps every stage; the inline path stops at owner_reg_done
# (no arena involved); the RPC fallback stamps store_rpc_done instead of
# alloc/copy/seal.  Like the hop tracer: opt-in, one call at a time, zero
# cost when disarmed (one `is not None` check per put).
PUT_ORDER = (
    "put_entry",        # put_object entry on the caller thread
    "serialize_done",   # value pickled (out-of-band buffers captured)
    "owner_reg_done",   # owner record + contained-ref pins registered
    "alloc_done",       # arena block allocated (mutex wait included)
    "copy_done",        # frame bytes copied into the arena
    "seal_done",        # object sealed (visible to readers)
    "store_rpc_done",   # RPC fallback: agent store_put round trip done
    "put_done",         # put_object returned (memory-store publication)
)

_put_armed: bool = False
_put_last: dict | None = None


def arm_put_trace() -> None:
    """One-shot: trace the next `ray_tpu.put` in this process."""
    global _put_armed
    _put_armed = True


def consume_put_arm() -> dict | None:
    """Claim the armed put trace (called by worker.put_object)."""
    global _put_armed
    if not _put_armed:
        return None
    _put_armed = False
    return {"put_entry": time.monotonic()}


def publish_put_trace(rec: dict) -> None:
    global _put_last
    _put_last = dict(rec)
    # Flight-recorder bridge: the armed put breakdown also lands in the
    # merged timeline (arena.put_stages + per-stage children), not only
    # in this driver-local slot.
    try:
        from ray_tpu._private import spans

        if spans.ENABLED:
            spans.emit_stamps(
                "arena.put_stages", rec, PUT_ORDER,
                attrs={k: rec[k] for k in ("path", "bytes")
                       if k in rec})
    except Exception:  # noqa: BLE001 - tracing must never fail a put
        pass


def take_put_trace() -> dict | None:
    """The most recent completed put trace, cleared on read."""
    global _put_last
    trace, _put_last = _put_last, None
    return trace


@contextmanager
def put_trace():
    """Trace ONE put's per-stage latency:

        with profiling.put_trace() as rec:
            ref = ray_tpu.put(big_array)
        table = profiling.put_breakdown_us(rec)

    The yielded dict gains "stages" (raw monotonic stamps plus path
    metadata) when the block exits; feed it to `put_breakdown_us`."""
    global _put_armed
    rec: dict = {}
    arm_put_trace()
    try:
        yield rec
    finally:
        rec["stages"] = take_put_trace()
        _put_armed = False


def put_breakdown_us(rec: dict) -> dict:
    """Per-stage latency table (microseconds between consecutive observed
    stamps, in PUT_ORDER) for a completed `put_trace` record, plus path
    metadata ("path", "bytes", "stream", "parallel_chunks") and the copy
    stage's effective bandwidth.  Empty when no put fired."""
    stages = dict(rec.get("stages") or {})
    if not stages:
        return {}
    present = [(k, stages[k]) for k in PUT_ORDER if k in stages]
    if len(present) < 2:
        return {}
    out: dict = {}
    prev_name, prev_t = present[0]
    for name, t in present[1:]:
        out[f"{prev_name}->{name}_us"] = round((t - prev_t) * 1e6, 1)
        prev_name, prev_t = name, t
    out["total_us"] = round((present[-1][1] - present[0][1]) * 1e6, 1)
    for key in ("path", "bytes", "stream", "parallel_chunks"):
        if key in stages:
            out[key] = stages[key]
    copy_us = out.get("alloc_done->copy_done_us")
    if copy_us and stages.get("bytes"):
        out["copy_gib_per_s"] = round(
            stages["bytes"] / (copy_us / 1e6) / (1 << 30), 2)
    return out


def put_stats() -> dict:
    """Per-process put-path counters: how many large puts wrote straight
    into the mmap'd arena vs silently degraded to the agent store_put
    RPC, and the first recorded fallback cause.  "put is slow" becomes
    diagnosable as "put is not using the arena"."""
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    return {"arena_puts": w._arena_puts,
            "rpc_fallback_puts": w._arena_fallbacks,
            "first_fallback_cause": w._arena_fallback_cause}


# ----------------------------------------------------- collective tracer
# Per-collective phase/byte accounting (ISSUE 5), mirroring the hop/put
# tracers: opt-in, one collective at a time per process, zero cost when
# disarmed (one `is not None` check per collective).  Unlike those two,
# a collective's phases REPEAT per hop (send/pull/reduce x (world-1)
# steps x pipeline chunks), so the record carries accumulated durations
# and byte counters rather than a linear stamp order:
#
#   schedule      "ring" | "tree" | "gather" (legacy)
#   op/bytes/world/rank   what ran
#   sent_bytes    payload bytes this rank put/deposited (the acceptance
#                 check: ring allreduce == 2*N*(world-1)/world, not
#                 O(world*N))
#   recv_bytes    payload bytes this rank pulled
#   send_us/pull_us/reduce_us/wait_us   accumulated phase time; pull
#                 runs on the prefetch thread, so phase sums can exceed
#                 total_us — that overlap is the point
#   hops          number of transport steps this rank took
COLLECTIVE_PHASES = ("send_us", "pull_us", "reduce_us", "wait_us")

_collective_armed: bool = False
_collective_last: dict | None = None


def arm_collective_trace() -> None:
    """One-shot: trace the next collective op in this process."""
    global _collective_armed
    _collective_armed = True


def blank_collective_rec() -> dict:
    """A live phase-accumulator record (the consume_collective_arm
    shape) for always-on consumers: the flight recorder's per-op
    collective spans reuse the schedules' existing stamp points by
    handing them this record even when no one-shot trace is armed."""
    return {"t0": time.monotonic(), "sent_bytes": 0, "recv_bytes": 0,
            "send_us": 0.0, "pull_us": 0.0, "reduce_us": 0.0,
            "wait_us": 0.0, "hops": 0}


def consume_collective_arm() -> dict | None:
    """Claim the armed trace (called by the collective module at op
    entry).  Returns a live record the schedule mutates in place."""
    global _collective_armed
    if not _collective_armed:
        return None
    _collective_armed = False
    return blank_collective_rec()


def publish_collective_trace(rec: dict) -> None:
    global _collective_last
    rec["total_us"] = round((time.monotonic() - rec.pop("t0")) * 1e6, 1)
    _collective_last = dict(rec)
    # Flight-recorder bridge: phase/byte accounting of the armed
    # collective lands in the merged timeline too.  (The collective
    # module also emits always-on per-op spans; this bridge covers the
    # one-shot tracer's richer record when both are active.)
    try:
        from ray_tpu._private import spans

        if spans.ENABLED:
            t1 = time.time()
            spans.emit(
                "collective.trace", t1 - rec["total_us"] / 1e6, t1,
                attrs={k: rec[k] for k in
                       ("schedule", "op", "bytes", "world", "rank",
                        "hops", "sent_bytes", "recv_bytes", "send_us",
                        "pull_us", "reduce_us", "wait_us") if k in rec})
    except Exception:  # noqa: BLE001 - tracing must never fail an op
        pass


def take_collective_trace() -> dict | None:
    """The most recent completed collective trace, cleared on read."""
    global _collective_last
    trace, _collective_last = _collective_last, None
    return trace


@contextmanager
def collective_trace():
    """Trace ONE collective's phase/byte breakdown:

        with profiling.collective_trace() as rec:
            col.allreduce(x, group_name="g")
        table = profiling.collective_breakdown_us(rec)

    The yielded dict gains "phases" when the block exits; feed it to
    `collective_breakdown_us`."""
    global _collective_armed
    rec: dict = {}
    arm_collective_trace()
    try:
        yield rec
    finally:
        rec["phases"] = take_collective_trace()
        _collective_armed = False


def collective_breakdown_us(rec: dict) -> dict:
    """Flat phase table for a completed `collective_trace` record:
    accumulated microseconds per phase, byte counters, and schedule
    metadata.  Empty when no collective fired."""
    phases = dict(rec.get("phases") or {})
    if not phases:
        return {}
    out: dict = {}
    for key in ("schedule", "op", "bytes", "world", "rank", "hops",
                "sent_bytes", "recv_bytes"):
        if key in phases:
            out[key] = phases[key]
    for key in COLLECTIVE_PHASES:
        if phases.get(key):
            out[key] = round(phases[key], 1)
    if "total_us" in phases:
        out["total_us"] = phases["total_us"]
        if phases.get("bytes"):
            out["gib_per_s"] = round(
                phases["bytes"] / (phases["total_us"] / 1e6) / (1 << 30),
                3)
    return out


@contextmanager
def profile(event_name: str, extra_data: dict | None = None):
    """Record a named span attributed to the current task (or the driver).

    with ray_tpu.profiling.profile("shuffle-partition"):
        ...
    """
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    owner = core.current_task_id or "driver"
    name = event_name if not extra_data else \
        f"{event_name} {extra_data}"
    core._record_event(owner, "PROFILE_BEGIN", name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        core._record_event(
            owner, "PROFILE_END",
            f"{name} ({(time.perf_counter() - t0) * 1e3:.2f}ms)")
