"""ctypes binding to the native shared-memory object store (native/store.cc).

Plasma-client analog (ray: src/ray/object_manager/plasma/client.cc): every
process on a host maps the same /dev/shm arena; sealed objects are read
zero-copy as memoryviews whose lifetime pins the object against eviction
(the reference's client hold/release protocol).

Objects are stored as a frame bundle:
    [u32 nframes][u64 len_0 .. len_{n-1}] then each frame 64-byte aligned.
Frame 0 is the pickle stream; frames 1.. are out-of-band buffers, so a numpy
array deserialized from the arena aliases arena memory directly.
"""
from __future__ import annotations

import concurrent.futures
import ctypes
import fcntl
import logging
import os
import struct
import threading
import subprocess
import time
import weakref
from concurrent.futures import ThreadPoolExecutor

logger = logging.getLogger(__name__)

# ---- put-path tuning: Config.put_stream_min_bytes /
# put_parallel_min_bytes are the single source of the defaults (worker/
# agent pass resolved values into Arena(...); bare Arena construction
# falls back to env-or-Config-default).  Kill switches for A/B
# debugging, read once per process like RAY_TPU_SYNC_FASTPATH:
#   RAY_TPU_PUT_STREAM=0    -> never call the non-temporal write kernel
#   RAY_TPU_PUT_PARALLEL=0  -> never split a frame across copy threads
#   RAY_TPU_ARENA_PREFAULT=0-> skip the free-space write-prefault pass
from ray_tpu._private import failpoints
from ray_tpu._private.config import DEFAULT as _DEFAULT_CONFIG

DEFAULT_STREAM_MIN = _DEFAULT_CONFIG.put_stream_min_bytes
DEFAULT_PARALLEL_MIN = _DEFAULT_CONFIG.put_parallel_min_bytes


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "1") != "0"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# Shared copy pool for the parallel chunked writer (pid-checked: a forked
# child must not reuse the parent's threads).  Sized to the machine, not
# the frame: min(cpu_count, chunks) threads are used per put.
_copy_pool: ThreadPoolExecutor | None = None
_copy_pool_pid: int | None = None
_copy_pool_lock = threading.Lock()


def _put_pool() -> ThreadPoolExecutor:
    global _copy_pool, _copy_pool_pid
    if _copy_pool is not None and _copy_pool_pid == os.getpid():
        return _copy_pool
    with _copy_pool_lock:
        if _copy_pool is None or _copy_pool_pid != os.getpid():
            _copy_pool = ThreadPoolExecutor(
                max_workers=max(1, (os.cpu_count() or 1) - 1),
                thread_name_prefix="raytpu-putcopy")
            _copy_pool_pid = os.getpid()
    return _copy_pool

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SAN = os.environ.get("RAYTPU_STORE_SANITIZE", "")
_SO_PATH = os.path.abspath(os.path.join(
    _NATIVE_DIR, "build",
    f"libraytpustore_{_SAN}.so" if _SAN else "libraytpustore.so"))
_CC_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "store.cc"))

_lib = None


def build_native_lib(src: str, out: str, extra_flags: list[str]) -> str:
    """Shared mtime-gated, flock'd g++ build for the in-tree native libs
    (the shm store and the C ABI frontend use the same recipe)."""
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if (os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(src)):
            return out
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
               "-o", out + ".tmp", src, *extra_flags]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(out + ".tmp", out)
    return out


SANITIZE_FLAGS = {
    # -O1 keeps stacks honest for reports; the robust-mutex/pin-table
    # code is where silent races would live (SURVEY §5 sanitizer row).
    "tsan": ["-fsanitize=thread", "-O1", "-g"],
    "asan": ["-fsanitize=address", "-O1", "-g"],
}


def _build_lib() -> None:
    """RAYTPU_STORE_SANITIZE=tsan|asan builds an instrumented variant to
    a separate path (tests/test_store_sanitize.py builds the standalone
    hammer binary the same way — a sanitized .so inside an uninstrumented
    python is not a supported TSAN mode, so the hammer is the real
    sanitizer entry point; this knob exists for ad-hoc ASAN runs)."""
    flags = SANITIZE_FLAGS.get(_SAN, [])
    build_native_lib(_CC_PATH, _SO_PATH, [*flags, "-lpthread", "-lrt"])


def load_lib():
    global _lib
    if _lib is not None:
        return _lib
    if (not os.path.exists(_SO_PATH)
            or os.path.getmtime(_SO_PATH) < os.path.getmtime(_CC_PATH)):
        _build_lib()
    lib = ctypes.CDLL(_SO_PATH)
    lib.rt_store_create.restype = ctypes.c_void_p
    lib.rt_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.rt_store_open.restype = ctypes.c_void_p
    lib.rt_store_open.argtypes = [ctypes.c_char_p]
    lib.rt_store_alloc.restype = ctypes.c_uint64
    lib.rt_store_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64]
    lib.rt_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_abort.restype = ctypes.c_int
    lib.rt_store_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_get.restype = ctypes.c_int
    lib.rt_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_uint64),
                                 ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_store_contains.restype = ctypes.c_int
    lib.rt_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_delete.restype = ctypes.c_int
    lib.rt_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_stats.argtypes = [ctypes.c_void_p] + \
        [ctypes.POINTER(ctypes.c_uint64)] * 3
    lib.rt_store_base.restype = ctypes.c_void_p
    lib.rt_store_base.argtypes = [ctypes.c_void_p]
    lib.rt_store_mapped_size.restype = ctypes.c_uint64
    lib.rt_store_mapped_size.argtypes = [ctypes.c_void_p]
    lib.rt_store_sweep_dead.restype = ctypes.c_int
    lib.rt_store_sweep_dead.argtypes = [ctypes.c_void_p]
    lib.rt_store_pin_overflow.restype = ctypes.c_uint64
    lib.rt_store_pin_overflow.argtypes = [ctypes.c_void_p]
    lib.rt_store_oldest.restype = ctypes.c_int
    lib.rt_store_oldest.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_peek.restype = ctypes.c_int
    lib.rt_store_peek.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_store_write_stream.restype = None
    lib.rt_store_write_stream.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                          ctypes.c_void_p, ctypes.c_uint64]
    lib.rt_store_stream_mode.restype = ctypes.c_int
    lib.rt_store_stream_mode.argtypes = []
    lib.rt_store_prefault_free.restype = ctypes.c_uint64
    lib.rt_store_prefault_free.argtypes = [ctypes.c_void_p]
    lib.rt_store_scan.restype = ctypes.c_uint32
    lib.rt_store_scan.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint32]
    lib.rt_store_pin_scan.restype = ctypes.c_uint32
    lib.rt_store_pin_scan.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_uint32]
    lib.rt_store_close.argtypes = [ctypes.c_void_p]
    lib.rt_store_unlink.argtypes = [ctypes.c_char_p]
    _lib = lib
    return lib


def _align64(n: int) -> int:
    return (n + 63) & ~63


def _bundle_layout(frame_lens: list[int]) -> tuple[int, list[int]]:
    """Return (total size, per-frame offsets) for a frame bundle."""
    header = 4 + 8 * len(frame_lens)
    offsets = []
    pos = _align64(header)
    for ln in frame_lens:
        offsets.append(pos)
        pos = _align64(pos + ln)
    return pos, offsets


class Arena:
    """One mapped shared-memory arena (create on agents, open on workers)."""

    def __init__(self, name: str, capacity: int | None = None,
                 create: bool = False, *, stream_min: int | None = None,
                 parallel_min: int | None = None):
        self.lib = load_lib()
        self.name = name
        self.retune(stream_min, parallel_min)
        if create:
            self.handle = self.lib.rt_store_create(
                name.encode(), ctypes.c_uint64(capacity or 0))
        else:
            self.handle = self.lib.rt_store_open(name.encode())
        if not self.handle:
            raise OSError(f"cannot map shm arena {name!r}")
        self.base = self.lib.rt_store_base(self.handle)
        self._created = create
        # Serializes pin-release finalizers against close(): a zero-copy
        # view's weakref.finalize fires on WHATEVER thread drops the last
        # reference (observed: the rpc IO thread), and an unsynchronized
        # handle check can pass just before close() munmaps the arena —
        # rt_store_release then touches unmapped memory (SIGSEGV caught
        # in-suite).  RLock, not Lock: a GC point inside close() itself
        # can run a finalizer reentrantly on the closing thread.
        self._pin_lock = threading.RLock()
        # Serializes prefault_free against close() WITHOUT touching
        # _pin_lock: the prefault pass runs ~100ms+ and pin-release
        # finalizers fire on the rpc IO thread — holding _pin_lock that
        # long would stall every RPC in the process.
        self._close_lock = threading.Lock()
        # Writable view over the whole mapping: frame payloads are copied in
        # with one memoryview slice assignment (no intermediate bytes()).
        size = self.lib.rt_store_mapped_size(self.handle)
        self._map = memoryview(
            (ctypes.c_ubyte * size).from_address(self.base)).cast("B")

    def retune(self, stream_min: int | None = None,
               parallel_min: int | None = None) -> None:
        """(Re-)apply put-path tuning: explicit args (worker/agent pass
        Config values) beat env beats defaults; the kill switches zero
        out a path.  Re-run post-fork on an inherited pre-warmed arena,
        whose zygote mapping never saw this worker's config."""
        self.stream_min = (stream_min if stream_min is not None else
                           _env_int("RAY_TPU_PUT_STREAM_MIN_BYTES",
                                    DEFAULT_STREAM_MIN))
        self.parallel_min = (parallel_min if parallel_min is not None else
                             _env_int("RAY_TPU_PUT_PARALLEL_MIN_BYTES",
                                      DEFAULT_PARALLEL_MIN))
        if not _env_flag("RAY_TPU_PUT_STREAM"):
            self.stream_min = 0x7FFFFFFFFFFFFFFF
        if not _env_flag("RAY_TPU_PUT_PARALLEL"):
            self.parallel_min = 0x7FFFFFFFFFFFFFFF

    # ---- write path ----
    def _frame_addr(self, f) -> tuple[int, object] | None:
        """(address, keepalive) of a frame's buffer, or None when the
        buffer exposes no raw pointer we can take (exotic read-only
        views fall back to slice assignment)."""
        if isinstance(f, bytes):
            # c_char_p points at the bytes object's internal buffer (the
            # returned keepalive holds the reference).
            p = ctypes.c_char_p(f)
            return ctypes.cast(p, ctypes.c_void_p).value, (f, p)
        mv = memoryview(f)
        try:
            c = (ctypes.c_char * mv.nbytes).from_buffer(mv)
        except (TypeError, BufferError):
            return None
        return ctypes.addressof(c), (mv, c)

    def _write_frame(self, dst_off: int, f, n: int,
                     trace: dict | None) -> None:
        """Copy one frame into the arena at data offset dst_off.

        Large frames go through the C streaming kernel (non-temporal
        stores — a 256 MiB put stops read-allocating the cache lines it
        is about to overwrite); frames >= parallel_min additionally split
        across min(cpu_count, chunks) GIL-releasing calls so multi-core
        boxes use more than one memory pipe.  A 1-core box always takes
        the single-call path."""
        src = self._frame_addr(f)
        if src is None:
            # Read-only exotic buffer: slice assignment (copies via the
            # buffer protocol).
            self._map[dst_off:dst_off + n] = memoryview(f).cast("B")
            return
        addr, _keep = src
        if n < self.stream_min:
            ctypes.memmove(self.base + dst_off, addr, n)
            return
        nthreads = min(os.cpu_count() or 1, 8)
        if n >= self.parallel_min and nthreads >= 2:
            # Page-aligned split: two threads must never write-fault the
            # same page.
            chunk = -(-n // nthreads) + 4095 & ~4095
            spans = [(s, min(chunk, n - s)) for s in range(0, n, chunk)]
            if trace is not None:
                trace["parallel_chunks"] = len(spans)
            pool = _put_pool()
            futs = [pool.submit(self.lib.rt_store_write_stream, self.handle,
                                dst_off + s, addr + s, ln)
                    for s, ln in spans[1:]]
            try:
                s0, ln0 = spans[0]
                self.lib.rt_store_write_stream(self.handle, dst_off + s0,
                                               addr + s0, ln0)
                for fut in futs:
                    fut.result()
            except BaseException:
                # Every pool thread must be OUT of the block before the
                # exception reaches put_frames' abort handler: abort
                # frees the block, and a still-running chunk write would
                # scribble over whatever gets allocated there next.
                for fut in futs:
                    fut.cancel()
                concurrent.futures.wait(futs)
                raise
        else:
            self.lib.rt_store_write_stream(self.handle, dst_off, addr, n)
        if trace is not None:
            trace["stream"] = bool(self.lib.rt_store_stream_mode())

    def put_frames(self, oid: bytes, frames: list,
                   trace: dict | None = None) -> bool:
        lens = [len(f) for f in frames]
        total, offsets = _bundle_layout(lens)
        off = self.lib.rt_store_alloc(self.handle, oid,
                                      ctypes.c_uint64(total))
        if trace is not None:
            trace["alloc_done"] = time.monotonic()
        if off == 0:
            return False
        try:
            # Failpoint window: the block is allocated (creating state)
            # but nothing is written yet — a crash here leaves a
            # half-created entry only the dead-pid sweep can reclaim; an
            # error must take the abort path below (the process is
            # alive, so nothing else would ever reclaim the block).
            if failpoints.ACTIVE:
                failpoints.fire("arena.alloc")
            hdr = struct.pack("<I", len(frames)) + struct.pack(
                f"<{len(lens)}Q", *lens)
            self._map[off:off + len(hdr)] = hdr
            for f, fo in zip(frames, offsets):
                n = len(f)
                if n:
                    self._write_frame(off + fo, f, n, trace)
            # Failpoint window: bytes copied, seal not yet reached — an
            # error here exercises the abort path below; a crash here
            # exercises the EOWNERDEAD/creating-state crash sweep.
            if failpoints.ACTIVE:
                failpoints.fire("arena.copy")
        except BaseException:
            # Never leak a creating-state block: abort the allocation so
            # the entry doesn't sit unreclaimable until a crash sweep.
            self.lib.rt_store_abort(self.handle, oid)
            raise
        if trace is not None:
            trace["copy_done"] = time.monotonic()
        self.lib.rt_store_seal(self.handle, oid)
        # Failpoint window: sealed but the owner record has not published
        # yet (worker.put_object's "put.publish" is the layer above).
        if failpoints.ACTIVE:
            failpoints.fire("arena.seal")
        if trace is not None:
            trace["seal_done"] = time.monotonic()
        return True

    def prefault_free(self) -> int:
        """Write-prefault this process's PTEs over the arena's free space
        (claim free blocks exclusively, touch one byte per page, abort) —
        see rt_store_prefault_free.  Without it, on kernels lacking
        MADV_POPULATE_WRITE every page of a process's first bulk put
        costs a write-protect fault: ~2-2.6x off peak copy bandwidth on
        the dev box.  Returns bytes touched; honors
        RAY_TPU_ARENA_PREFAULT=0."""
        if not _env_flag("RAY_TPU_ARENA_PREFAULT"):
            return 0
        with self._close_lock:
            if not self.handle:
                return 0
            return int(self.lib.rt_store_prefault_free(self.handle))

    # ---- read path ----
    def get_frames(self, oid: bytes) -> list | None:
        """Zero-copy read: returned memoryviews pin the object until GC'd."""
        mv = self.get_raw(oid)
        if mv is None:
            return None
        (nframes,) = struct.unpack_from("<I", mv, 0)
        lens = struct.unpack_from(f"<{nframes}Q", mv, 4)
        _, offsets = _bundle_layout(list(lens))
        return [mv[fo:fo + ln] for fo, ln in zip(offsets, lens)]

    def _release_pin(self, oid: bytes) -> None:
        with self._pin_lock:
            if self.handle:
                self.lib.rt_store_release(self.handle, oid)

    # ---- chunked-transfer raw access (node-to-node object plane) ----
    def get_raw(self, oid: bytes) -> memoryview | None:
        """Read-only view of the WHOLE frame bundle (header + payloads) —
        get_frames parses it, chunked pushes slice it.

        The returned view pins the object until collected.  The finalizer
        uses bound-method indirection, NOT a direct rt_store_release
        capture: a finalizer firing after close() must not touch the
        freed handle.  Read-only because sealed objects are immutable —
        a writable view would let `got += 1` silently corrupt the object
        for every reader on the node (ray: plasma fetched buffers are
        immutable)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if not self.lib.rt_store_get(self.handle, oid,
                                     ctypes.byref(off), ctypes.byref(size)):
            return None
        buf = (ctypes.c_ubyte * size.value).from_address(
            self.base + off.value)
        weakref.finalize(buf, self._release_pin, oid)
        return memoryview(buf).toreadonly()

    def get_raw_addr(self, oid: bytes) -> tuple[int, int, object] | None:
        """(address, size, release) of the WHOLE frame bundle for the
        same-host cross-arena copy path: the caller streams bytes
        straight out of this arena's mapping into another arena, then
        calls release() exactly once.  The pin taken here is the normal
        pid-attributed read pin — a crashed reader's pin is reclaimed by
        this arena's sweep, same as any zero-copy view."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if not self.lib.rt_store_get(self.handle, oid,
                                     ctypes.byref(off), ctypes.byref(size)):
            return None
        released = threading.Event()

        def release() -> None:
            if not released.is_set():
                released.set()
                self._release_pin(oid)
        return self.base + off.value, size.value, release

    def write_raw_from_addr(self, oid: bytes, offset: int, src_addr: int,
                            n: int) -> bool:
        """write_raw from a raw source address (another mapped arena):
        big spans ride the same non-temporal streaming kernel as local
        puts — the same-host object transfer is ONE copy at memory
        bandwidth, no zmq hop."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if not self.lib.rt_store_peek(self.handle, oid, ctypes.byref(off),
                                      ctypes.byref(size)):
            return False
        if offset + n > size.value:
            return False
        if n >= self.stream_min:
            self.lib.rt_store_write_stream(self.handle, off.value + offset,
                                           src_addr, n)
        else:
            ctypes.memmove(self.base + off.value + offset, src_addr, n)
        return True

    def read_bundle_copy(self, oid: bytes) -> bytes | None:
        """COPY of the whole frame bundle with the pin released before
        returning.  The spill path uses this instead of get_raw: a
        finalizer-released pin only drops when GC breaks the ctypes
        reference cycle, which would make spill-then-delete flaky."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if not self.lib.rt_store_get(self.handle, oid,
                                     ctypes.byref(off), ctypes.byref(size)):
            return None
        try:
            return ctypes.string_at(self.base + off.value, size.value)
        finally:
            self.lib.rt_store_release(self.handle, oid)

    def create_raw(self, oid: bytes, total: int) -> bool:
        """Allocate an unsealed region for chunked assembly."""
        return self.lib.rt_store_alloc(
            self.handle, oid, ctypes.c_uint64(total)) != 0

    def peek_raw(self, oid: bytes) -> bool:
        """True while a CREATING-state block exists for oid (another
        puller's in-flight assembly).  Distinguishes create_raw's two
        failure causes: duplicate id (wait for the sibling) vs capacity
        (spill to make room)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        return bool(self.lib.rt_store_peek(self.handle, oid,
                                           ctypes.byref(off),
                                           ctypes.byref(size)))

    def write_raw(self, oid: bytes, offset: int, chunk: bytes) -> bool:
        """Write one chunk into a creating-state region (DCN pulls land
        here); big chunks ride the same streaming kernel as local puts."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if not self.lib.rt_store_peek(self.handle, oid, ctypes.byref(off),
                                      ctypes.byref(size)):
            return False
        n = len(chunk)
        if offset + n > size.value:
            return False
        src = self._frame_addr(chunk)
        if src is not None and n >= self.stream_min:
            self.lib.rt_store_write_stream(self.handle, off.value + offset,
                                           src[0], n)
        else:
            ctypes.memmove(self.base + off.value + offset, chunk, n)
        return True

    def seal_raw(self, oid: bytes) -> bool:
        return self.lib.rt_store_seal(self.handle, oid) == 0

    def abort_raw(self, oid: bytes) -> None:
        self.lib.rt_store_abort(self.handle, oid)

    def contains(self, oid: bytes) -> bool:
        return bool(self.lib.rt_store_contains(self.handle, oid))

    def delete(self, oid: bytes) -> bool:
        """True when the object is gone (freed now or already absent);
        False when a live pin blocked the delete."""
        return self.lib.rt_store_delete(self.handle, oid) == 0

    def stats(self) -> dict:
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        num = ctypes.c_uint64()
        self.lib.rt_store_stats(self.handle, ctypes.byref(used),
                                ctypes.byref(cap), ctypes.byref(num))
        return {"used": used.value, "capacity": cap.value,
                "num_objects": num.value,
                "pin_overflow":
                    int(self.lib.rt_store_pin_overflow(self.handle))}

    def sweep_dead(self) -> int:
        """Reclaim pins held by crash-killed processes (agent-side)."""
        return int(self.lib.rt_store_sweep_dead(self.handle))

    _SCAN_MAX = 65536          # kIndexSlots: one record per index entry

    def scan_objects(self) -> list[dict]:
        """Memory-ledger view of every live entry: object id, size,
        sealed/creating state, pin count, creator pid, LRU tick.  One
        pass under the arena mutex — harvest/sentinel cadence only,
        never a hot path."""
        buf = ctypes.create_string_buffer(48 * self._SCAN_MAX)
        n = int(self.lib.rt_store_scan(self.handle, buf, self._SCAN_MAX))
        out = []
        for i in range(n):
            rec = buf.raw[i * 48:(i + 1) * 48]
            size, tick = struct.unpack_from("<QQ", rec, 16)
            state, pins = struct.unpack_from("<II", rec, 32)
            (creator_pid,) = struct.unpack_from("<i", rec, 40)
            out.append({"object_id": rec[:16], "size": size,
                        "lru_tick": tick,
                        "sealed": state == 2, "pins": pins,
                        "creator_pid": creator_pid})
        return out

    def scan_pins(self) -> list[tuple[bytes, int]]:
        """(object id, reader pid) of every live pid-attributed read
        pin — the leak sentinel cross-references these against live
        pids."""
        buf = ctypes.create_string_buffer(20 * 8192)
        n = int(self.lib.rt_store_pin_scan(self.handle, buf, 8192))
        return [(buf.raw[i * 20:i * 20 + 16],
                 struct.unpack_from("<i", buf.raw, i * 20 + 16)[0])
                for i in range(n)]

    def oldest(self) -> bytes | None:
        """LRU unpinned sealed object id — the next spill candidate."""
        out = ctypes.create_string_buffer(16)
        if self.lib.rt_store_oldest(self.handle, out):
            return out.raw
        return None

    def close(self) -> None:
        # _close_lock first (waits out an in-flight prefault pass, which
        # never takes _pin_lock), then _pin_lock for the finalizer
        # protocol.  Lock order close_lock -> pin_lock, nobody nests the
        # other way.
        with self._close_lock, self._pin_lock:
            if not self.handle:
                return
            # Null the handle BEFORE unmapping: a reentrant finalizer
            # (GC at a bytecode boundary inside this block, RLock lets
            # it through) must see a closed arena and no-op.
            handle, self.handle = self.handle, None
            self.lib.rt_store_close(handle)
            if self._created:
                self.lib.rt_store_unlink(self.name.encode())


def _cleanup_stale_arenas() -> None:
    """Unlink arenas whose owning agent (pid suffix) is gone — crash-killed
    agents can't unlink their own /dev/shm segment."""
    try:
        for f in os.listdir("/dev/shm"):
            if not f.startswith("raytpu_"):
                continue
            try:
                pid = int(f.rsplit("_", 1)[-1])
            except ValueError:
                continue
            if not os.path.exists(f"/proc/{pid}"):
                try:
                    os.unlink(os.path.join("/dev/shm", f))
                except OSError:
                    pass
    except OSError:
        pass


class NativeStoreBackend:
    """Agent-side node-store backend over the native arena (drop-in for
    object_store._DictBackend)."""

    def __init__(self, node_id: str, capacity: int, config=None):
        _cleanup_stale_arenas()
        self._name = f"/raytpu_{node_id[:16]}_{os.getpid()}"
        self.arena = Arena(
            self._name, capacity, create=True,
            stream_min=getattr(config, "put_stream_min_bytes", None),
            parallel_min=getattr(config, "put_parallel_min_bytes", None))
        # Write-prefault the fresh arena's pages off the boot path: at
        # create time every block is free and no client is connected, so
        # the claim/touch/abort pass races nothing.
        threading.Thread(target=self._prefault, daemon=True,
                         name="raytpu-arena-prefault").start()

    def _prefault(self) -> None:
        try:
            touched = self.arena.prefault_free()
            if touched:
                logger.debug("arena %s prefaulted %d MiB of free space",
                             self._name, touched >> 20)
        except Exception:  # noqa: BLE001 - prefault is best-effort
            logger.debug("arena prefault failed", exc_info=True)

    @property
    def shm_name(self) -> str:
        return self._name

    def put(self, oid: bytes, frames: list) -> bool:
        return self.arena.put_frames(oid, frames)

    def get(self, oid: bytes):
        return self.arena.get_frames(oid)

    def contains(self, oid: bytes) -> bool:
        return self.arena.contains(oid)

    def delete(self, oid: bytes) -> bool:
        return self.arena.delete(oid)

    def pin(self, oid: bytes, delta: int) -> None:
        pass  # pinning is per-reader via get_frames views

    def sweep_dead(self) -> int:
        return self.arena.sweep_dead()

    def scan_objects(self) -> list[dict]:
        return self.arena.scan_objects()

    def scan_pins(self) -> list[tuple[bytes, int]]:
        return self.arena.scan_pins()

    def oldest(self) -> bytes | None:
        return self.arena.oldest()

    # Chunked-transfer raw region access (see Arena)
    def get_raw(self, oid: bytes):
        return self.arena.get_raw(oid)

    def get_raw_addr(self, oid: bytes):
        return self.arena.get_raw_addr(oid)

    def write_raw_from_addr(self, oid: bytes, offset: int, src_addr: int,
                            n: int) -> bool:
        return self.arena.write_raw_from_addr(oid, offset, src_addr, n)

    def get_bundle_copy(self, oid: bytes) -> bytes | None:
        return self.arena.read_bundle_copy(oid)

    def create_raw(self, oid: bytes, total: int) -> bool:
        return self.arena.create_raw(oid, total)

    def peek_raw(self, oid: bytes) -> bool:
        return self.arena.peek_raw(oid)

    def write_raw(self, oid: bytes, offset: int, chunk) -> bool:
        return self.arena.write_raw(oid, offset, chunk)

    def seal_raw(self, oid: bytes) -> bool:
        return self.arena.seal_raw(oid)

    def abort_raw(self, oid: bytes) -> None:
        self.arena.abort_raw(oid)

    def stats(self) -> dict:
        return self.arena.stats()

    def close(self) -> None:
        self.arena.close()


# ---------------------------------------------- zygote prefork warm arena
# The warm-fork spawner maps + write-prefaults the node arena ONCE before
# forking workers; every child then inherits the fully-populated mapping
# (VMA and PTEs ride along with fork), so a 24-worker boot storm pays the
# ~250ms 512MB prefault once instead of 24 times — and each child's own
# warm_arena pass degenerates to a ~ms touch of already-present pages.
_PREFORK_ARENA: "tuple[str, Arena] | None" = None


def preheat_for_fork(name: str) -> None:
    """Zygote-side, pre-fork: map + prefault the arena once.  Import/map
    only — no threads, no sockets (the zygote safety rules)."""
    global _PREFORK_ARENA
    if _PREFORK_ARENA is not None and _PREFORK_ARENA[0] == name:
        return
    arena = Arena(name)
    try:
        arena.prefault_free()
    except Exception:  # noqa: BLE001 - warm is best-effort
        pass
    # Children skip their own warm pass: the inherited PTEs are the
    # warm state (worker.warm_arena checks this flag).
    arena.prewarmed = True
    _PREFORK_ARENA = (name, arena)


def take_prefork_arena(name: str) -> "Arena | None":
    """Worker-side, post-fork: the inherited pre-warmed mapping for this
    node's store, or None (cold spawn / different store).  The caller
    must retune() it — the zygote's mapping never saw worker config."""
    if _PREFORK_ARENA is not None and _PREFORK_ARENA[0] == name:
        return _PREFORK_ARENA[1]
    return None
