"""Cluster flight recorder: always-on per-process span ring buffer.

Analog of ray's two-piece tracing story — OpenTelemetry spans around
every task (ray: python/ray/util/tracing/tracing_helper.py) plus the
core worker's task-event buffer aggregated centrally (ray:
src/ray/core_worker task events -> GCS) — collapsed into one mechanism:
every process keeps a fixed-size ring of completed spans, each stamped
with a W3C-style trace context (trace_id / span_id / parent) that rides
the existing task "trace" header across worker→agent→controller→replica
hops.  Harvest is pull-based: the `spans` RPC verb (same
controller→agents→workers broadcast fan-out as the `failpoints` verb)
drains every buffer; `ray_tpu.tracing.harvest()` merges them by
trace_id into one connected timeline per serve request / train step.

Design contract (the tentpole's cost rules):

- **Always on** (kill switch ``RAY_TPU_TRACE=0``): every instrumented
  site is ``if spans.ENABLED: ...`` — one module-flag truth test when
  disabled, the failpoints discipline.
- **Lock-light emit**: the ring is a preallocated list + an
  ``itertools.count`` cursor (``next()`` is GIL-atomic), so recording a
  span is a dict build + one list-slot store — no lock, no allocation
  beyond the record, safe from any thread including the rpc IO thread
  (it never blocks).
- **Bounded**: ``RAY_TPU_TRACE_BUFFER`` slots per process (default
  4096); older spans are overwritten, never flushed synchronously.
- **Cross-process**: trace context propagates through the task header
  (worker._build_task_payload consults `task_trace_context()`; the
  executing worker adopts the header via `adopt_task_trace` /
  the ``current_trace`` fallback), so a span opened on the driver
  parents spans recorded inside replicas on other hosts with zero new
  wire fields.

Clock: spans carry wall time (`time.time()`, shared across processes on
a host — the same basis as the task-event timeline), so buffers from
different processes merge onto one timeline directly.
"""
from __future__ import annotations

import contextvars
import itertools
import os
import time
from contextlib import contextmanager

ENV_VAR = "RAY_TPU_TRACE"
BUF_VAR = "RAY_TPU_TRACE_BUFFER"


def _env_on() -> bool:
    v = os.environ.get(ENV_VAR)
    if v is None:
        return True
    return v not in ("0", "false", "False", "")


# Module flag read by every instrumented site (the failpoints ACTIVE
# discipline): True unless RAY_TPU_TRACE=0.
ENABLED = _env_on()

_CAPACITY = max(256, int(os.environ.get(BUF_VAR, "4096") or "4096"))
_buf: list = [None] * _CAPACITY
_cursor = itertools.count()
_emitted = 0                    # approximate (racy +=); stats only
_pid = os.getpid()
_span_seq = itertools.count(1)
_proc_label: str | None = None
# Process identity for harvest dedup: bare pid collides across HOSTS
# (containerized nodes all start at low pids), so replies carry a
# boot token — same interpreter through several fan-out legs → same
# token; same pid on two hosts → different tokens.
_boot = f"{_pid:x}-{time.time_ns():x}"

# Current trace context: (trace_id, span_id).  A ContextVar so async
# replica handlers carry their own request's context across awaits —
# the per-task worker attributes can't (they are process-global and
# async actor methods interleave).
_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "raytpu_span_ctx", default=None)


def set_enabled(on: bool) -> None:
    """Flip the recorder and mirror the choice into os.environ so
    processes spawned from here inherit it (same-run A/B: the bench
    runs one workload leg with the recorder on, one with it off)."""
    global ENABLED
    ENABLED = bool(on)
    os.environ[ENV_VAR] = "1" if on else "0"


def set_process_label(label: str) -> None:
    """Name this process in harvest output ("driver", "agent:<node>",
    "worker:<id>", "controller") — attached once per harvest reply,
    not per span."""
    global _proc_label
    _proc_label = label


def proc_label() -> str:
    return _proc_label or f"pid:{_pid}"


def _new_id() -> str:
    """Process-unique, cheap, process-stable span/trace id (16 hex
    chars: pid + per-process counter — never `hash()`, never random
    state that a fork would duplicate)."""
    return f"{_pid & 0xFFFFFFFF:08x}{next(_span_seq) & 0xFFFFFFFF:08x}"


def _append(rec: dict) -> None:
    global _emitted
    i = next(_cursor)
    _buf[i % _CAPACITY] = rec
    _emitted = i + 1


def current() -> tuple | None:
    """The active (trace_id, span_id), from the contextvar when a span
    is open here, else from the executing task's "trace" header — the
    hop that makes any code running inside a task automatically part of
    its submitter's trace."""
    c = _ctx.get()
    if c is not None:
        return c
    try:
        from ray_tpu._private.worker import _global_worker

        w = _global_worker
        tc = w.current_trace if w is not None else None
    except Exception:  # noqa: BLE001 - no runtime in this process
        return None
    if tc:
        return (tc["trace_id"], tc["span_id"])
    return None


# The recorder-facing alias (library code reads better with it).
capture = current


def task_trace_context() -> dict | None:
    """The active context shaped like the task header's "trace" dict,
    for worker._build_task_payload: a task submitted under an open span
    joins the span's trace with the span as its parent."""
    c = _ctx.get()
    if c is None:
        return None
    return {"trace_id": c[0], "span_id": c[1]}


def adopt_task_trace(trace: dict | None):
    """Install a task header's trace context into the current
    (async) execution context; returns a reset token (or None).  Sync
    executor paths don't need this — they set worker.current_trace and
    `current()` falls back to it — but async actor methods interleave
    on one loop, so each handler task must carry its own copy."""
    if not trace:
        return None
    return _ctx.set((trace["trace_id"], trace["span_id"]))


@contextmanager
def context(ctx: tuple | None):
    """Install an explicit (trace_id, span_id) context for a block —
    how threads executing deferred work (collective op threads, engine
    loops) re-join the request that submitted it."""
    if ctx is None:
        yield
        return
    token = _ctx.set(tuple(ctx))
    try:
        yield
    finally:
        _ctx.reset(token)


def _clean_attrs(attrs: dict | None) -> dict:
    """msgpack-safe attrs: the harvest verb ships records over RPC, so
    one exotic value must not poison a whole buffer."""
    if not attrs:
        return {}
    out = {}
    for k, v in attrs.items():
        if isinstance(v, bool) or v is None or isinstance(v, str):
            out[str(k)] = v
        elif isinstance(v, (int, float)):
            out[str(k)] = v
        else:
            out[str(k)] = str(v)
    return out


def emit(name: str, t0: float, t1: float | None = None,
         ctx: tuple | None = None, attrs: dict | None = None) -> None:
    """Record one completed span.  `ctx` is an explicit (trace_id,
    parent_span_id) pair — e.g. captured at request submission and
    replayed from the engine loop thread; None uses `current()`; with
    no context anywhere the span roots its own trace."""
    if not ENABLED:
        return
    c = ctx if ctx is not None else current()
    if c is not None:
        tid, par = c
    else:
        tid, par = _new_id(), ""
    _append({"tid": tid, "sid": _new_id(), "par": par or "",
             "name": name, "t0": t0,
             "t1": time.time() if t1 is None else t1,
             "pid": _pid, "attrs": _clean_attrs(attrs)})


def emit_task(trace: dict | None, name: str, t0: float,
              err: str | None = None) -> None:
    """Record a task-execution span from its header trace: span_id IS
    the task id, so spans recorded inside the task (which parent to the
    header's span_id) connect to it across the process boundary."""
    if not ENABLED or not trace:
        return
    rec = {"tid": trace["trace_id"], "sid": trace["span_id"],
           "par": trace.get("parent_span") or "", "name": name,
           "t0": t0, "t1": time.time(), "pid": _pid, "attrs": {}}
    if err:
        rec["attrs"] = {"error": err}
    _append(rec)


def emit_stamps(prefix: str, stamps: dict, order: tuple,
                ctx: tuple | None = None,
                attrs: dict | None = None) -> None:
    """Bridge a legacy tracer record (monotonic-clock stamp sequence,
    e.g. the hop/put tracers' dicts) into child spans: one span per
    consecutive stamp pair, re-anchored onto the wall clock at publish
    time so they land on the merged timeline."""
    if not ENABLED:
        return
    present = [(k, stamps[k]) for k in order
               if isinstance(stamps.get(k), (int, float))]
    if len(present) < 2:
        return
    offset = time.time() - time.monotonic()
    c = ctx if ctx is not None else current()
    parent_tid, parent_sid = c if c is not None else (_new_id(), "")
    # One parent span for the whole stamped operation...
    psid = _new_id()
    _append({"tid": parent_tid, "sid": psid, "par": parent_sid,
             "name": prefix, "t0": present[0][1] + offset,
             "t1": present[-1][1] + offset, "pid": _pid,
             "attrs": _clean_attrs(attrs)})
    # ...and one child per stamp-to-stamp segment.
    for (a, ta), (b, tb) in zip(present, present[1:]):
        _append({"tid": parent_tid, "sid": _new_id(), "par": psid,
                 "name": f"{prefix}.{a}->{b}", "t0": ta + offset,
                 "t1": tb + offset, "pid": _pid, "attrs": {}})


@contextmanager
def span(name: str, attrs: dict | None = None, ctx: tuple | None = None):
    """Record a span around a block; nested spans (and tasks submitted
    inside the block) parent to it.  Yields the span's mutable attrs
    dict so the block can annotate what it learned (replica picked,
    cache score, bytes moved):

        with spans.span("serve.route") as sp:
            rid = pick(...)
            sp["replica"] = rid
    """
    if not ENABLED:
        yield {}
        return
    parent = ctx if ctx is not None else current()
    sid = _new_id()
    tid = parent[0] if parent is not None else _new_id()
    par = parent[1] if parent is not None else ""
    token = _ctx.set((tid, sid))
    live_attrs = dict(attrs) if attrs else {}
    t0 = time.time()
    err = None
    try:
        yield live_attrs
    except BaseException as e:  # noqa: BLE001 - recorded, re-raised
        err = f"{type(e).__name__}"
        raise
    finally:
        _ctx.reset(token)
        if err is not None:
            live_attrs["error"] = err
        _append({"tid": tid, "sid": sid, "par": par, "name": name,
                 "t0": t0, "t1": time.time(), "pid": _pid,
                 "attrs": _clean_attrs(live_attrs)})


def snapshot(trace_id: str | None = None) -> list[dict]:
    """Copy the live ring (oldest-first-ish; callers sort by t0).  The
    list() copy is a C-level slice under the GIL — concurrent emits may
    land or miss, never tear a record."""
    out = [r for r in list(_buf) if r is not None]
    if trace_id:
        out = [r for r in out if r["tid"] == trace_id]
    return out


def clear() -> None:
    # Cursor and emitted reset WITH the buffer: `dropped` counts ring
    # overwrites since the last clear, not spans a harvest collected
    # (a fresh count may race one in-flight emit; the stats are
    # advisory).
    global _buf, _cursor, _emitted
    _buf = [None] * _CAPACITY
    _cursor = itertools.count()
    _emitted = 0


def stats() -> dict:
    return {"enabled": ENABLED, "capacity": _CAPACITY,
            "emitted": _emitted,
            "buffered": sum(1 for r in _buf if r is not None),
            "dropped": max(0, _emitted - _CAPACITY)}


def control(h: dict) -> dict:
    """The `spans` RPC verb body, shared by worker/agent/controller
    handlers.  ops: collect (drain-free read, optional trace_id filter
    and clear), clear, stats, enable (flip the recorder live)."""
    op = h.get("op", "collect")
    if op == "collect":
        out = snapshot(h.get("trace_id"))
        if h.get("clear"):
            clear()
        return {"spans": out, "pid": _pid, "boot": _boot,
                "proc": proc_label(), **stats()}
    if op == "clear":
        clear()
        return {"pid": _pid, "boot": _boot, "proc": proc_label(),
                **stats()}
    if op == "enable":
        set_enabled(bool(h.get("on", True)))
        return {"pid": _pid, "boot": _boot, "proc": proc_label(),
                **stats()}
    if op == "stats":
        return {"pid": _pid, "boot": _boot, "proc": proc_label(),
                **stats()}
    raise ValueError(f"spans verb: unknown op {op!r}")


def _after_fork_child() -> None:
    # The ring's contents belong to the parent; the child records its
    # own.  Ids re-key on the child pid so they stay process-unique.
    global _pid, _buf, _cursor, _span_seq, _emitted, _proc_label, _boot
    _pid = os.getpid()
    _buf = [None] * _CAPACITY
    _cursor = itertools.count()
    _span_seq = itertools.count(1)
    _emitted = 0
    _proc_label = None
    _boot = f"{_pid:x}-{time.time_ns():x}"


os.register_at_fork(after_in_child=_after_fork_child)
