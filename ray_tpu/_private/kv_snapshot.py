"""External TCP KV store for controller snapshots.

The head-node-loss durability backend (ray analog: the GCS Redis store
client, src/ray/gcs/store_client/redis_store_client.cc:1, selected in
gcs_server.cc:41-78 as StorageType::REDIS_PERSIST): controller snapshots
are written to a store that lives OUTSIDE the head host, so a
replacement controller on a fresh host restores cluster state the local
file backend cannot provide.  Redis is absent from this environment, so
the store itself is part of the framework: a dependency-free TCP server
(`python -m ray_tpu._private.kv_snapshot --port N [--dir d]`) speaking a
length-prefixed binary protocol, and a `kv://host:port/name` client
registered as a builtin snapshot scheme (controller.py
make_snapshot_storage).

Wire format (all u32 big-endian):
  request : cmd(1) keylen(4) key vallen(4) val
  response: status(1) vallen(4) val
  cmds    : S=set  G=get  D=del  P=ping  A=auth (val carries the token)
  status  : '+'=ok  '-'=miss  '!'=error (val carries the message)

Auth: when the server is started with a shared secret (RAY_TPU_KV_TOKEN
env var or the --token flag), every connection must present it in an
`A` frame before any other command; a missing or wrong token gets a
clear '!' error and the connection is closed.  The client sends the
frame automatically when its own RAY_TPU_KV_TOKEN is set.  WITHOUT a
token the server trusts its network completely — anyone who can reach
the port can read and overwrite controller snapshots — so an unset
token is only appropriate on a loopback interface or an isolated
cluster-management network (the same trust assumption as an
unauthenticated Redis for the reference's GCS).
"""
from __future__ import annotations

import os
import socket
import struct
import threading


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("kv peer closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> tuple[bytes, bytes, bytes]:
    cmd = _recv_exact(sock, 1)
    (klen,) = struct.unpack(">I", _recv_exact(sock, 4))
    key = _recv_exact(sock, klen) if klen else b""
    (vlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    val = _recv_exact(sock, vlen) if vlen else b""
    return cmd, key, val


def _send_resp(sock: socket.socket, status: bytes, val: bytes = b"") -> None:
    sock.sendall(status + struct.pack(">I", len(val)) + val)


class KvStoreServer:
    """Tiny durable KV: in-memory dict, optionally mirrored to one file
    per key under `data_dir` (loaded at boot), so the STORE process can
    itself restart without losing snapshots.  One thread per connection —
    snapshot traffic is one controller writing every snapshot period."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 data_dir: str | None = None, token: str | None = None):
        self.data: dict[bytes, bytes] = {}
        self.data_dir = data_dir
        # Shared-secret auth (see module docstring).  None/"" = open.
        self.token = (token if token is not None
                      else os.environ.get("RAY_TPU_KV_TOKEN", "")) or ""
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            for fn in os.listdir(data_dir):
                if fn.endswith(".kv"):
                    with open(os.path.join(data_dir, fn), "rb") as f:
                        self.data[bytes.fromhex(fn[:-3])] = f.read()
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.addr = f"{host}:{self._sock.getsockname()[1]}"
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="kv-store", daemon=True)

    def start(self) -> "KvStoreServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _persist(self, key: bytes, val: bytes | None) -> None:
        if not self.data_dir:
            return
        path = os.path.join(self.data_dir, key.hex() + ".kv")
        if val is None:
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            return
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(val)
        os.replace(tmp, path)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        authed = not self.token
        try:
            while True:
                cmd, key, val = _recv_frame(conn)
                if cmd == b"A":
                    # Tokenless servers accept (and ignore) the frame so a
                    # token-configured client still talks to them.
                    if self.token and val.decode(
                            "utf-8", "replace") != self.token:
                        _send_resp(conn, b"!",
                                   b"auth failed: RAY_TPU_KV_TOKEN "
                                   b"mismatch with kv store")
                        # The client pipelines its command behind the
                        # auth frame (one sendall); consume it before
                        # close() so unread bytes don't turn the close
                        # into an RST that can discard the error
                        # response in flight.
                        try:
                            _recv_frame(conn)
                        except (ConnectionError, OSError):
                            pass
                        return
                    authed = True
                    _send_resp(conn, b"+")
                    continue
                if not authed:
                    _send_resp(conn, b"!",
                               b"auth required: kv store has a token; "
                               b"set RAY_TPU_KV_TOKEN on the client")
                    return
                with self._lock:
                    if cmd == b"S":
                        self.data[key] = val
                        self._persist(key, val)
                        _send_resp(conn, b"+")
                    elif cmd == b"G":
                        got = self.data.get(key)
                        if got is None:
                            _send_resp(conn, b"-")
                        else:
                            _send_resp(conn, b"+", got)
                    elif cmd == b"D":
                        self.data.pop(key, None)
                        self._persist(key, None)
                        _send_resp(conn, b"+")
                    elif cmd == b"P":
                        _send_resp(conn, b"+", b"pong")
                    else:
                        _send_resp(conn, b"!", b"unknown cmd")
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class KvClient:
    """Blocking client; one short-lived connection per op so it survives
    store restarts without reconnect logic (snapshot cadence is seconds,
    not microseconds — simplicity beats pooling here)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 token: str | None = None):
        self.host, self.port, self.timeout = host, port, timeout
        self.token = (token if token is not None
                      else os.environ.get("RAY_TPU_KV_TOKEN", "")) or ""

    def _call(self, cmd: bytes, key: bytes,
              val: bytes = b"") -> tuple[bytes, bytes]:
        with socket.create_connection((self.host, self.port),
                                      timeout=self.timeout) as s:
            payload = cmd + struct.pack(">I", len(key)) + key \
                + struct.pack(">I", len(val)) + val
            if self.token:
                # One-connection-per-op protocol: prepend the auth frame
                # and read its response before the real one.
                tok = self.token.encode()
                payload = (b"A" + struct.pack(">I", 0)
                           + struct.pack(">I", len(tok)) + tok + payload)
            s.sendall(payload)
            if self.token:
                auth_status = _recv_exact(s, 1)
                (alen,) = struct.unpack(">I", _recv_exact(s, 4))
                auth_out = _recv_exact(s, alen) if alen else b""
                if auth_status == b"!":
                    raise RuntimeError(f"kv store error: {auth_out!r}")
            status = _recv_exact(s, 1)
            (vlen,) = struct.unpack(">I", _recv_exact(s, 4))
            out = _recv_exact(s, vlen) if vlen else b""
        if status == b"!":
            raise RuntimeError(f"kv store error: {out!r}")
        return status, out

    def set(self, key: bytes, val: bytes) -> None:
        self._call(b"S", key, val)

    def get(self, key: bytes) -> bytes | None:
        status, val = self._call(b"G", key)
        return val if status == b"+" else None

    def delete(self, key: bytes) -> None:
        self._call(b"D", key)

    def ping(self) -> bool:
        try:
            return self._call(b"P", b"")[1] == b"pong"
        except (OSError, ConnectionError):
            return False


class KvSnapshotStorage:
    """SnapshotStorage over `kv://host:port/name` (registered as a
    builtin scheme in controller.make_snapshot_storage).  Write failures
    propagate to the controller's snapshot loop, which logs and retries
    next period — same contract as the file backend on a full disk."""

    def __init__(self, uri: str):
        rest = uri[len("kv://"):]
        hostport, _, name = rest.partition("/")
        host, _, port = hostport.rpartition(":")
        if not port or not port.isdigit():
            # A portless URI used to surface as a bare
            # ValueError('myhost') from int() — name the expected form.
            raise ValueError(
                f"invalid kv snapshot URI {uri!r}: expected "
                "kv://HOST:PORT/NAME (e.g. kv://127.0.0.1:7379/"
                f"controller), got host:port part {hostport!r} "
                "with a missing or non-numeric port")
        self.client = KvClient(host or "127.0.0.1", int(port))
        self.key = (name or "controller").encode()

    def read(self) -> bytes | None:
        return self.client.get(self.key)

    def write(self, blob: bytes) -> None:
        self.client.set(self.key, blob)


def main() -> None:
    import argparse
    import json
    import sys
    import time

    ap = argparse.ArgumentParser(description="ray_tpu snapshot KV store")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--dir", default=None,
                    help="persist keys to this directory")
    ap.add_argument("--token", default=None,
                    help="shared-secret auth token (default: "
                         "RAY_TPU_KV_TOKEN env var; empty = open)")
    args = ap.parse_args()
    srv = KvStoreServer(args.host, args.port, args.dir,
                        token=args.token).start()
    print(json.dumps({"kv_addr": srv.addr}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
        sys.exit(0)


if __name__ == "__main__":
    main()
