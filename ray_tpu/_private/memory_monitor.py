"""Host memory monitor + OOM worker-killing policy.

Analog of the reference's MemoryMonitor (ray: src/ray/common/memory_monitor.h:52,
polled every 250ms against `memory_usage_threshold`, ray_config_def.h:65-78)
and the raylet killing policies (ray: src/ray/raylet/worker_killing_policy
_retriable_fifo.h — prefer retriable, newest first; spare actors while task
workers remain).

Reads cgroup v2 limits when the process is containerized, /proc/meminfo
otherwise — the same dual source the reference uses.
"""
from __future__ import annotations

import logging
import os
import time

logger = logging.getLogger(__name__)

_CGROUP_CUR = "/sys/fs/cgroup/memory.current"
_CGROUP_MAX = "/sys/fs/cgroup/memory.max"


def _read_int(path: str) -> int | None:
    try:
        with open(path) as f:
            raw = f.read().strip()
        if raw == "max":
            return None
        return int(raw)
    except (OSError, ValueError):
        return None


def _cgroup_reclaimable() -> int:
    """Reclaimable page cache inside the cgroup (inactive_file): counted
    in memory.current but freed under pressure, so it must not trigger
    kills (ray: MemoryMonitor subtracts it, memory_monitor.cc)."""
    try:
        with open("/sys/fs/cgroup/memory.stat") as f:
            for line in f:
                if line.startswith("inactive_file "):
                    return int(line.split()[1])
    except (OSError, ValueError):
        pass
    return 0


def memory_usage_fraction() -> float:
    """Used/total for the tightest enclosing limit (cgroup else host)."""
    cur, cap = _read_int(_CGROUP_CUR), _read_int(_CGROUP_MAX)
    if cur is not None and cap is not None and cap > 0:
        return max(0, cur - _cgroup_reclaimable()) / cap
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0.0
    if not total or avail is None:
        return 0.0
    return 1.0 - avail / total


def pick_oom_victim(workers: list) -> object | None:
    """Choose the worker to kill under memory pressure.

    Policy (ray: worker_killing_policy_retriable_fifo.h): prefer leased
    task workers (their tasks retry via the submitter's retry budget) over
    actor workers (stateful; restart costs more), and within a class kill
    the NEWEST first — it has done the least work.  Idle/starting workers
    hold no task and are never victims (they die via the idle reaper).
    """
    leased = [w for w in workers if w.state == "leased"
              and not w.is_device_worker]
    actors = [w for w in workers if w.state == "actor"
              and not w.is_device_worker]
    pool = leased or actors
    if not pool:
        return None
    return max(pool, key=lambda w: w.started_at)


class MemoryMonitor:
    """Threshold tracker with a kill cooldown (a kill takes a moment to
    return memory; re-killing every poll would cascade)."""

    def __init__(self, threshold: float, min_kill_interval_s: float = 2.0):
        self.threshold = threshold
        self.min_kill_interval_s = min_kill_interval_s
        self._last_kill = 0.0

    def should_kill(self, usage: float | None = None) -> bool:
        usage = memory_usage_fraction() if usage is None else usage
        if usage < self.threshold:
            return False
        now = time.monotonic()
        if now - self._last_kill < self.min_kill_interval_s:
            return False
        self._last_kill = now
        return True
