"""Value serialization for tasks, actors, and objects.

Analog of the reference's SerializationContext
(ray: python/ray/_private/serialization.py:114): cloudpickle for code +
pickle protocol 5 out-of-band buffers so large numpy/jax host arrays are
carried as raw frames (zero-copy into/out of the shared-memory store) rather
than being copied into the pickle stream.

ObjectRefs embedded in values are hooked at (de)serialization time so the
owner can track borrowers, mirroring the reference's reducer hooks for
ObjectRef (ray: python/ray/_private/serialization.py _object_ref_reducer).
"""
from __future__ import annotations

import pickle
import threading
from typing import Any, Callable

import cloudpickle


class SerializedValue:
    """A pickled value plus its out-of-band buffers.

    frames[0] is the pickle stream; frames[1:] are raw PickleBuffer payloads.
    """

    __slots__ = ("frames", "contained_refs")

    def __init__(self, frames: list[bytes], contained_refs: list):
        self.frames = frames
        self.contained_refs = contained_refs

    @property
    def total_bytes(self) -> int:
        return sum(len(f) for f in self.frames)

    def to_payload(self) -> list[bytes]:
        return self.frames


# Thread-local capture of ObjectRefs encountered while pickling a value.
_capture = threading.local()


def _note_ref(ref) -> None:
    lst = getattr(_capture, "refs", None)
    if lst is not None:
        lst.append(ref)


class _Pickler(cloudpickle.CloudPickler):
    def __init__(self, file, buffer_callback):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)

    def persistent_id(self, obj):  # noqa: D401 - hook, not docstring target
        return None

    def reducer_override(self, obj):
        from ray_tpu.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            _note_ref(obj)
            return (ObjectRef._from_serialized, (obj.binary(), obj.owner_addr))
        custom = _custom_serializers.get(obj.__class__)
        if custom is not None:
            ser, deser = custom
            # The DESERIALIZER function rides the pickle stream by value
            # (cloudpickle), so receiving workers need no registration
            # (ray: util/serialization.py register_serializer — same
            # one-sided contract).
            return (deser, (ser(obj),))
        return super().reducer_override(obj)


# Exact-type custom reducers (ray: SerializationContext
# _register_cloudpickle_serializer).  Keyed by class; subclasses do NOT
# inherit the serializer (matching the reference).
_custom_serializers: dict = {}


_SAFE_SCALARS = frozenset({type(None), bool, int, float, complex, str,
                           bytes, bytearray})
_SAFE_CONTAINERS = frozenset({list, tuple, set, frozenset})

try:
    import numpy as _np
except Exception:  # noqa: BLE001
    _np = None


def _stdlib_picklable(v: Any) -> bool:
    """True when the C pickler provably produces the SAME result cloudpickle
    would: exact builtin scalar/container types, object-free numpy arrays,
    and ObjectRefs.  Everything else (instances of user classes — possibly
    defined in __main__, which stdlib pickles by broken reference but
    cloudpickle by value — functions, jax arrays, subclasses) falls back to
    the CloudPickler."""
    t = v.__class__
    if t in _SAFE_SCALARS:
        return True
    if t is dict:
        return all(_stdlib_picklable(k) and _stdlib_picklable(x)
                   for k, x in v.items())
    if t in _SAFE_CONTAINERS:
        return all(_stdlib_picklable(x) for x in v)
    if _np is not None and t is _np.ndarray:
        return not v.dtype.hasobject
    from ray_tpu.object_ref import ObjectRef

    return t is ObjectRef


def serialize(value: Any) -> SerializedValue:
    import io

    buffers: list[pickle.PickleBuffer] = []
    _capture.refs = []
    try:
        fast = False
        try:
            fast = _stdlib_picklable(value)
        except RecursionError:
            fast = False
        if fast:
            # Hot path: the C pickler (~10x the pure-Python CloudPickler
            # for small values).  ObjectRef capture still works — its
            # __reduce__ calls _note_ref.
            stream = pickle.dumps(value, protocol=5,
                                  buffer_callback=buffers.append)
        else:
            sink = io.BytesIO()
            _Pickler(sink, buffers.append).dump(value)
            stream = sink.getvalue()
        frames: list = [stream]
        for b in buffers:
            raw = b.raw()   # 1-D C-contiguous "B" view (raises otherwise)
            # Large buffers stay zero-copy views into the source object
            # (numpy/jax host arrays) all the way to the shm arena / wire —
            # the reference's plasma path has the same discipline.  Small
            # ones are snapshotted: cheap, and frees the source immediately.
            frames.append(raw if raw.nbytes >= 1 << 20 else raw.tobytes())
        return SerializedValue(frames, list(_capture.refs))
    finally:
        _capture.refs = None


def _note_deser_ref(ref) -> None:
    """Capture ObjectRefs materialized during a deserialize_with_refs call
    (borrower tracking, ray: serialization.py ObjectRef deserializer hook)."""
    lst = getattr(_capture, "deser_refs", None)
    if lst is not None:
        lst.append(ref)


def deserialize(frames: list[bytes | memoryview]) -> Any:
    bufs = [pickle.PickleBuffer(f) for f in frames[1:]]
    return pickle.loads(frames[0], buffers=bufs)


def deserialize_with_refs(frames: list[bytes | memoryview]) -> tuple[Any, list]:
    """Deserialize and also return the ObjectRefs contained in the value
    (the executing side of the borrow protocol)."""
    bufs = [pickle.PickleBuffer(f) for f in frames[1:]]
    _capture.deser_refs = []
    try:
        value = pickle.loads(frames[0], buffers=bufs)
        return value, list(_capture.deser_refs)
    finally:
        _capture.deser_refs = None


def dumps_function(fn: Callable) -> bytes:
    """Pickle a remote function/actor class for export to the controller KV
    (ray: python/ray/_private/function_manager.py:195 export)."""
    return cloudpickle.dumps(fn)


def loads_function(b: bytes) -> Callable:
    return cloudpickle.loads(b)
