"""ray-tpu CLI: start/stop/status/memory/list/summary/timeline/job.

Analog of ray: python/ray/scripts/scripts.py (ray start/stop/status/
memory/timeline/… 2619 LoC; command registry at the bottom).  Invoke as
`python -m ray_tpu.scripts.cli <command>`.

Head state lives in /tmp/ray_tpu_head.json so `stop`/`status`/drivers on
the same box can find the cluster (ray: /tmp/ray/ray_current_cluster).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

HEAD_STATE = "/tmp/ray_tpu_head.json"


def _read_state() -> dict:
    try:
        with open(HEAD_STATE) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return {}


def _require_address(args) -> str:
    addr = getattr(args, "address", None) or \
        os.environ.get("RAY_TPU_ADDRESS") or _read_state().get("address")
    if not addr:
        sys.exit("no cluster: run `ray-tpu start --head` or pass --address")
    return addr


def cmd_start(args) -> None:
    """ray: `ray start --head` / `ray start --address=...`."""
    from ray_tpu._private.config import Config

    config = Config()
    if args.head:
        from ray_tpu.api import _read_json_line

        # start_new_session + RAY_TPU_DAEMONIZE: the head must outlive this
        # CLI process — `ray-tpu stop` kills it by pidfile.
        denv = {**os.environ, "RAY_TPU_DAEMONIZE": "1"}
        cprocs = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.controller",
             "--config-json", config.to_json()], stdout=subprocess.PIPE,
            start_new_session=True, env=denv)
        cinfo = _read_json_line(cprocs)
        controller_addr = cinfo["controller_addr"]
        agent_args = [sys.executable, "-m", "ray_tpu._private.node_agent",
                      "--controller", controller_addr,
                      "--config-json", config.to_json()]
        if args.resources:
            agent_args += ["--resources-json", args.resources]
        aprocs = subprocess.Popen(agent_args, stdout=subprocess.PIPE,
                                  start_new_session=True, env=denv)
        ainfo = _read_json_line(aprocs)
        with open(HEAD_STATE, "w") as f:
            json.dump({"address": controller_addr,
                       "pids": [cprocs.pid, aprocs.pid],
                       "node_id": ainfo["node_id"]}, f)
        print(f"started head: controller at {controller_addr}")
        print(f"attach drivers with ray_tpu.init(address="
              f"{controller_addr!r}) or RAY_TPU_ADDRESS={controller_addr}")
    else:
        addr = args.address or _require_address(args)
        agent_args = [sys.executable, "-m", "ray_tpu._private.node_agent",
                      "--controller", addr,
                      "--config-json", config.to_json()]
        if args.resources:
            agent_args += ["--resources-json", args.resources]
        from ray_tpu.api import _read_json_line

        proc = subprocess.Popen(
            agent_args, stdout=subprocess.PIPE, start_new_session=True,
            env={**os.environ, "RAY_TPU_DAEMONIZE": "1"})
        info = _read_json_line(proc)
        st = _read_state()
        st.setdefault("pids", []).append(proc.pid)
        with open(HEAD_STATE, "w") as f:
            json.dump(st, f)
        print(f"joined {addr} as node {info['node_id'][:12]}")


def cmd_stop(_args) -> None:
    """ray: `ray stop`."""
    st = _read_state()
    n = 0
    for pid in st.get("pids", []):
        try:
            os.kill(pid, signal.SIGTERM)
            n += 1
        except ProcessLookupError:
            pass
    time.sleep(0.5)
    for pid in st.get("pids", []):
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    try:
        os.unlink(HEAD_STATE)
    except FileNotFoundError:
        pass
    print(f"stopped {n} head processes")


def _attach(args):
    import ray_tpu

    ray_tpu.init(address=_require_address(args))
    return ray_tpu


def cmd_status(args) -> None:
    """ray: `ray status` — node/resource overview, plus the
    autoscaler's posted demand floors per requester (serve/elastic):
    "why are we holding N nodes" answerable from the CLI."""
    rt = _attach(args)
    nodes = rt.nodes()
    print(f"{len(nodes)} node(s)")
    for n in nodes:
        print(f"  {n['node_id'][:12]} {n['state']:6} "
              f"resources={n['resources']} available={n['available']}")
    _print_demand_floors()


def _print_demand_floors() -> None:
    """The request_resources floors each requester posted (the
    autoscaler v2 reconciler's merged_demand input), per requester and
    summed — empty floors are skipped.  One kv_multiget round trip
    (autoscaler.demand_floors, shared with merged_demand)."""
    from ray_tpu._private.worker import global_worker
    from ray_tpu.autoscaler.autoscaler import demand_floors

    core = global_worker()
    try:
        floors = demand_floors(core, core.controller_addr)
    except Exception as e:  # noqa: BLE001 - head without kv: skip
        print(f"autoscaler demand: unavailable ({e})")
        return
    rows = []
    total_cpus, total_bundles = 0.0, 0
    for requester, payload in floors.items():
        cpus = float(payload.get("num_cpus", 0) or 0)
        bundles = payload.get("bundles") or []
        if not cpus and not bundles:
            continue
        rows.append((requester, cpus, bundles))
        total_cpus += cpus
        total_bundles += len(bundles)
    if not rows:
        print("autoscaler demand: no floors posted")
        return
    print("autoscaler demand floors (request_resources):")
    for requester, cpus, bundles in sorted(rows):
        extra = f" bundles={bundles}" if bundles else ""
        print(f"  {requester:<12} num_cpus={cpus:g}{extra}")
    print(f"  merged: num_cpus={total_cpus:g} "
          f"bundles={total_bundles}")


def _fmt_bytes(n: int | float | None) -> str:
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B")
        n /= 1024
    return f"{n:.1f}GiB"


def cmd_memory(args) -> None:
    """ray: `ray memory` — the per-callsite grouped object table over
    the cluster ledger harvest (owner, tag, size, tier, pins,
    borrowers, locations), followed by per-node store usage and the
    leak sentinel's gauges."""
    rt = _attach(args)
    from ray_tpu.utils import state

    # ONE cluster fan-out feeds both the table and the leak footer
    # (list_objects + summarize_objects would broadcast twice).
    harvest = state._harvest_memory(5000, 30.0)
    rows, _diag = state._merge_object_rows(harvest[0], harvest[1])
    rows.sort(key=lambda r: -r["size"])
    filters = []
    if getattr(args, "tag", None):
        filters.append(("tag", "=", args.tag))
    rows = state._apply_filters(rows, filters)
    if getattr(args, "json", False):
        print(json.dumps(rows, indent=2, default=str))
        return
    groups: dict[str, list] = {}
    for r in rows:
        groups.setdefault(r["callsite"], []).append(r)
    print(f"Grouping by callsite; {len(rows)} object(s), "
          f"{_fmt_bytes(sum(r['size'] for r in rows))} total\n")
    hdr = (f"{'OBJECT ID':<16} {'SIZE':>10} {'TIER':<7} {'PINS':>4} "
           f"{'REFS':>5} {'BORROW':>6} {'AGE_S':>7} {'TAG':<16} "
           f"{'OWNER':<22} NODES")
    for site, grp in sorted(groups.items(),
                            key=lambda kv: -sum(r["size"]
                                                for r in kv[1])):
        total = sum(r["size"] for r in grp)
        print(f"--- {site}  ({len(grp)} object(s), "
              f"{_fmt_bytes(total)})")
        print(f"    {hdr}")
        for r in sorted(grp, key=lambda r: -r["size"]):
            nodes = ",".join(r.get("store_nodes") or
                             ([r["node"]] if r["node"] else []))
            pin_pids = ",".join(
                str(p) for h in r["pin_holders"] for p in h["pids"])
            print(f"    {r['object_id'][:16]:<16} "
                  f"{_fmt_bytes(r['size']):>10} {r['tier']:<7} "
                  f"{r['pins']:>4} {r['local_refs']:>5} "
                  f"{r['borrowers']:>6} "
                  f"{(r['age_s'] if r['age_s'] is not None else '?'):>7} "
                  f"{r['tag']:<16} {str(r['owner']):<22} {nodes}"
                  + (f"  pin_pids={pin_pids}" if pin_pids else ""))
        print()
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    total_used = total_objs = 0
    for n in rt.nodes():
        if n["state"] != "ALIVE":
            continue
        try:
            stats, _ = core.call(n["agent_addr"], "store_stats", {},
                                 timeout=10.0)
        except Exception as e:  # noqa: BLE001
            print(f"  {n['node_id'][:12]} store unreachable: {e}")
            continue
        used, cap = stats.get("used", 0), stats.get("capacity", 0)
        print(f"  {n['node_id'][:12]} store {used / 1e6:.1f}MB / "
              f"{cap / 1e6:.1f}MB  objects={stats.get('num_objects', 0)}  "
              f"spilled={stats.get('spilled_objects', 0)} "
              f"({stats.get('spilled_bytes', 0) / 1e6:.1f}MB on disk)")
        total_used += used
        total_objs += stats.get("num_objects", 0)
    print(f"cluster: {total_used / 1e6:.1f}MB in {total_objs} object(s) "
          "in node stores")
    leaks = state._summarize_from(*harvest)["cluster"]["leaks"]
    print(f"leak sentinel: orphan_pin_bytes="
          f"{_fmt_bytes(leaks['arena_orphan_pin_bytes'])} "
          f"unreachable_owner_bytes="
          f"{_fmt_bytes(leaks.get('objects_unreachable_owner_bytes'))}")


def _parse_series_key(key: str) -> tuple[str, dict]:
    """`name{k=v,k2=v2}` → (name, tags) — the telemetry series-key
    shape (_private/telemetry.series_key)."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    tags = {}
    for part in rest.rstrip("}").split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            tags[k] = v
    return name, tags


def cmd_top(args) -> None:
    """Live cluster telemetry view over the timeline harvest: per
    serve deployment/engine req/s, queue depth, cache hit rate; per
    train gang step time.  --once prints one frame; --json dumps the
    raw merged timeline instead of the table."""
    _attach(args)
    from ray_tpu import telemetry

    prefixes = ["serve_llm_", "serve_replica_", "train_"]

    def frame() -> None:
        # Bounded window: latest() needs one point and rate() a 30s
        # window — don't re-ship every process's full 5-minute ring
        # per screen refresh.
        doc = telemetry.timeseries(series=prefixes, fresh=True,
                                   since=time.time() - 60.0)
        if args.json:
            print(json.dumps(doc, indent=2, default=str))
            return
        # Group series keys per display row; each row can hold SEVERAL
        # keys per metric (one per replica / rank) — aggregate across
        # them, and within a key across processes (latest_by_proc):
        # an N-replica gauge read as one "latest" answers for one
        # replica of N.
        engines: dict[str, dict] = {}
        deployments: dict[str, dict] = {}
        gangs: dict[str, dict] = {}
        for key in doc["series"]:
            name, tags = _parse_series_key(key)
            if name.startswith("serve_llm_") and "engine" in tags:
                row = engines.setdefault(tags["engine"], {})
            elif name.startswith("serve_replica_") and \
                    "deployment" in tags:
                label = (f"{tags['app']}/{tags['deployment']}"
                         if tags.get("app") else tags["deployment"])
                row = deployments.setdefault(label, {})
            elif name.startswith("train_") and "trial" in tags:
                row = gangs.setdefault(tags["trial"], {})
            else:
                continue
            row.setdefault(name, []).append(key)

        def agg_latest(keys: list[str], how: str) -> float | None:
            vals = [v for k in keys
                    for v in telemetry.latest_by_proc(doc, k)]
            if not vals:
                return None
            if how == "sum":
                return sum(vals)
            if how == "max":
                return max(vals)
            return sum(vals) / len(vals)           # mean

        def agg_rate(keys: list[str]) -> float:
            return sum(telemetry.rate(doc, k) or 0.0 for k in keys)

        print(f"ray-tpu top — {time.strftime('%H:%M:%S')}  "
              f"({len(doc['procs'])} process(es)"
              + (", PARTIAL: " + "; ".join(doc["diagnostics"])
                 if doc["diagnostics"] else "") + ")")
        if engines:
            print(f"  {'ENGINE':<20} {'REQ/S':>7} {'QUEUE':>6} "
                  f"{'HIT%':>6} {'OCCUP':>6}")
            for eng, row in sorted(engines.items()):
                rps = agg_rate(row.get("serve_llm_requests_completed",
                                       []))
                q = agg_latest(row.get("serve_llm_queue_depth", []),
                               "sum")
                hit = agg_latest(row.get("serve_llm_prefix_hit_rate",
                                         []), "mean")
                occ = agg_latest(row.get("serve_llm_batch_occupancy",
                                         []), "mean")
                print(f"  {eng:<20} {rps:>7.2f} "
                      f"{int(q) if q is not None else '?':>6} "
                      f"{100 * hit if hit is not None else 0:>6.1f} "
                      f"{occ if occ is not None else 0:>6.2f}")
        if deployments:
            print(f"  {'DEPLOYMENT':<20} {'REQ/S':>7} {'ONGOING':>8}")
            for dep, row in sorted(deployments.items()):
                rps = agg_rate(row.get("serve_replica_processed", []))
                ong = agg_latest(row.get("serve_replica_ongoing", []),
                                 "sum")
                print(f"  {dep:<20} {rps:>7.2f} "
                      f"{int(ong) if ong is not None else 0:>8}")
        if gangs:
            print(f"  {'TRAIN GANG':<20} {'STEP_S':>8} {'STEPS/S':>8}")
            for trial, row in sorted(gangs.items()):
                step = agg_latest(row.get("train_step_s", []), "max")
                nranks = max(1, len(row.get("train_reported_steps",
                                            [])))
                sps = agg_rate(row.get("train_reported_steps", [])) \
                    / nranks
                print(f"  {trial:<20} "
                      f"{step if step is not None else 0:>8.3f} "
                      f"{sps:>8.2f}")
        if not (engines or deployments or gangs):
            print("  no serve/train series yet "
                  "(is RAY_TPU_TELEMETRY=0, or nothing running?)")

    if args.once or args.json:
        frame()
        return
    try:
        while True:
            print("\033[2J\033[H", end="")     # clear + home
            frame()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass


def cmd_slow(args) -> None:
    """The N worst requests in the flight recorder with their critical
    paths — "which stage moved p99" from a terminal.  Also prints the
    aggregate per-stage attribution and the harvest's dropped-span
    diagnostics (a wrapped ring reads as truncated, never silent)."""
    _attach(args)
    from ray_tpu import tracing

    spans, diags = tracing.harvest(with_diagnostics=True)
    trees = tracing.trace_trees(spans)
    if args.match:
        # --match scopes BOTH the worst-N list and the aggregate
        # attribution — otherwise boot/control-plane traces drown the
        # request stages in the summary table.
        trees = {tid: roots for tid, roots in trees.items()
                 if len(roots) == 1
                 and roots[0]["span"]["name"].startswith(args.match)}
    rows = tracing.slowest(trees, n=args.n, prefix=args.match or None)
    if args.json:
        print(json.dumps({"slowest": rows,
                          "attribution": tracing.attribution(trees),
                          "diagnostics": diags}, indent=2,
                         default=str))
        return
    if not rows:
        print("no connected traces in the flight recorder"
              + (f" matching {args.match!r}" if args.match else ""))
    for i, row in enumerate(rows):
        print(f"#{i + 1}  {row['name']}  {row['ms']:.1f}ms  "
              f"trace={row['trace_id']}  [{row['proc']}]")
        for seg in row["path"]:
            rel = (seg["t0"] - row["t0"]) * 1000.0
            print(f"    +{rel:>9.1f}ms {seg['ms']:>9.1f}ms  "
                  f"{'. ' * seg['depth']}{seg['name']} "
                  f"[{seg['proc']}]")
    attr = tracing.attribution(trees)
    if attr["requests"]:
        print(f"\nattribution over {attr['requests']} request(s) "
              f"(total p50={attr['total_ms']['p50']:.1f}ms "
              f"p99={attr['total_ms']['p99']:.1f}ms):")
        for name, st in attr["stages"].items():
            print(f"  {st['share_pct']:>5.1f}%  {name:<28} "
                  f"p50={st['p50_ms']:.1f}ms p99={st['p99_ms']:.1f}ms "
                  f"n={st['count']}")
    if diags["dropped_total"] or diags["errors"]:
        print(f"\nTRUNCATED harvest: {diags['dropped_total']} span(s) "
              f"overwritten in per-process rings; "
              f"{len(diags['errors'])} failed fan-out leg(s)")


def cmd_list(args) -> None:
    """ray: `ray list actors|nodes|tasks|objects|placement-groups|jobs`."""
    _attach(args)
    from ray_tpu.utils import state

    kind = args.kind.replace("-", "_")
    fn = {"actors": state.list_actors, "nodes": state.list_nodes,
          "tasks": state.list_tasks, "objects": state.list_objects,
          "placement_groups": state.list_placement_groups,
          "jobs": state.list_jobs}.get(kind)
    if fn is None:
        sys.exit(f"unknown kind {args.kind!r}")
    print(json.dumps(fn(), indent=2, default=str))


def cmd_summary(args) -> None:
    """ray: `ray summary tasks|actors`."""
    _attach(args)
    from ray_tpu.utils import state

    fn = {"tasks": state.summarize_tasks,
          "actors": state.summarize_actors,
          "objects": state.summarize_objects}.get(args.kind)
    if fn is None:
        sys.exit(f"unknown kind {args.kind!r}")
    print(json.dumps(fn(), indent=2))


def cmd_stack(_args) -> None:
    """ray: `ray stack` — dump all-thread stacks of every live runtime
    process (controller/agents/workers) on this host."""
    from ray_tpu._private.stack_dump import collect

    print(collect())


def cmd_timeline(args) -> None:
    """ray: `ray timeline` — Chrome trace JSON from task events."""
    rt = _attach(args)
    events = rt.timeline()
    trace = []
    for ev in events:
        trace.append({"name": ev.get("name") or ev.get("state", "?"),
                      "ph": "i",
                      "ts": ev.get("ts", 0) * 1e6,
                      "pid": ev.get("worker_id", "")[:12],
                      "tid": ev.get("task_id", "")[:12],
                      "args": ev})
    out = args.out or "ray-tpu-timeline.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace)} events to {out}")


def cmd_job(args) -> None:
    """ray: `ray job submit/status/logs/stop/list`."""
    os.environ.setdefault("RAY_TPU_ADDRESS", _require_address(args))
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    if args.job_cmd == "submit":
        import shlex

        parts = ([args.job_id] if args.job_id else []) + args.entrypoint
        jid = client.submit_job(entrypoint=shlex.join(parts))
        print(jid)
        if args.wait:
            print(client.wait_until_finished(jid))
            print(client.get_job_logs(jid))
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.job_id))
    elif args.job_cmd == "stop":
        print(client.stop_job(args.job_id))
    elif args.job_cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2, default=str))


def cmd_serve(args) -> None:
    """ray: `serve deploy/status/shutdown` — declarative config apply."""
    os.environ.setdefault("RAY_TPU_ADDRESS", _require_address(args))
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(address="auto")
    if args.serve_cmd == "deploy":
        with open(args.config_file) as f:
            config = json.load(f)
        from ray_tpu.serve.schema import apply_config

        routes = apply_config(config)
        print(json.dumps({"applied": routes}, indent=2))
    elif args.serve_cmd == "status":
        print(json.dumps(serve.status(), indent=2, default=str))
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")


def cmd_up(args) -> None:
    """ray: `ray up cluster.yaml` — create/update the configured cluster."""
    from ray_tpu.autoscaler import launcher

    config = launcher.load_config(args.config_file)
    summary = launcher.up(config, dry_run=args.dry_run,
                          controller_addr=getattr(args, "address", None)
                          or os.environ.get("RAY_TPU_ADDRESS"))
    print(json.dumps(summary, indent=2))


def cmd_attach(args) -> None:
    """ray: `ray attach cluster.yaml` — interactive shell on the head."""
    import subprocess

    from ray_tpu.autoscaler import launcher

    config = launcher.load_config(args.config_file)
    argv = launcher.attach_command(
        config, controller_addr=getattr(args, "address", None))
    if args.dry_run:
        print(json.dumps({"argv": argv}))
        return
    raise SystemExit(subprocess.call(argv))


def cmd_exec(args) -> None:
    """ray: `ray exec cluster.yaml 'cmd'` — run a command on the head."""
    import subprocess

    from ray_tpu.autoscaler import launcher

    config = launcher.load_config(args.config_file)
    argv = launcher.exec_command(
        config, args.command, controller_addr=getattr(args, "address", None))
    if args.dry_run:
        print(json.dumps({"argv": argv}))
        return
    raise SystemExit(subprocess.call(argv))


def cmd_submit(args) -> None:
    """ray: `ray submit cluster.yaml script.py args...` — copy + run."""
    import subprocess

    from ray_tpu.autoscaler import launcher

    config = launcher.load_config(args.config_file)
    argvs = launcher.submit_commands(
        config, args.script, args.script_args,
        controller_addr=getattr(args, "address", None))
    if args.dry_run:
        print(json.dumps({"argvs": argvs}))
        return
    for argv in argvs:
        rc = subprocess.call(argv)
        if rc:
            raise SystemExit(rc)


def cmd_get_head_ip(args) -> None:
    """ray: `ray get-head-ip cluster.yaml`."""
    from ray_tpu.autoscaler import launcher

    config = launcher.load_config(args.config_file)
    print(launcher.get_head_ip(
        config, controller_addr=getattr(args, "address", None)))


def cmd_down(args) -> None:
    """ray: `ray down cluster.yaml` — tear the cluster down."""
    from ray_tpu.autoscaler import launcher

    config = launcher.load_config(args.config_file)
    summary = launcher.down(config, dry_run=args.dry_run,
                            controller_addr=getattr(args, "address", None)
                            or os.environ.get("RAY_TPU_ADDRESS"))
    print(json.dumps(summary, indent=2))


def cmd_drain(args) -> None:
    """ray: `ray drain-node` — graceful drain: the node leaves the
    scheduling view, running work finishes, heartbeats continue."""
    addr = _require_address(args)
    import asyncio

    from ray_tpu._private.rpc import RpcClient

    async def _go():
        cli = RpcClient(address=addr)
        reply, _ = await cli.call("drain_node",
                                  {"node_id": args.node_id}, timeout=30.0)
        cli.close()
        return reply

    print(json.dumps(asyncio.run(_go()), indent=2))


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(prog="ray-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("up", help="create/update a cluster from YAML")
    sp.add_argument("config_file")
    sp.add_argument("--dry-run", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a YAML-configured cluster")
    sp.add_argument("config_file")
    sp.add_argument("--dry-run", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("attach", help="interactive ssh to the head node")
    sp.add_argument("config_file")
    sp.add_argument("--dry-run", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_attach)

    sp = sub.add_parser("exec", help="run a shell command on the head")
    sp.add_argument("config_file")
    sp.add_argument("command")
    sp.add_argument("--dry-run", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_exec)

    sp = sub.add_parser("submit",
                        help="copy a script to the head and run it")
    sp.add_argument("--dry-run", action="store_true")
    sp.add_argument("--address")
    sp.add_argument("config_file")
    sp.add_argument("script")
    # REMAINDER: everything after the script belongs to the script —
    # plain nargs="*" would reject dash-prefixed args (`job.py --n 2`).
    sp.add_argument("script_args", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("get-head-ip", help="print the head node address")
    sp.add_argument("config_file")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_get_head_ip)

    sp = sub.add_parser("drain-node", help="gracefully drain one node")
    sp.add_argument("node_id")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_drain)

    sp = sub.add_parser("start", help="start head or join a cluster")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address")
    sp.add_argument("--resources", help='JSON, e.g. \'{"CPU": 8}\'')
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop local head processes")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser(
        "top", help="live telemetry view (serve req/s, queue depth, "
                    "hit rate; train step time)")
    sp.add_argument("--address")
    sp.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    sp.add_argument("--json", action="store_true",
                    help="dump the raw merged timeline")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser(
        "slow", help="N worst traced requests with critical paths + "
                     "per-stage attribution")
    sp.add_argument("--address")
    sp.add_argument("-n", type=int, default=5)
    sp.add_argument("--match", help="filter on root span name prefix "
                                    "(e.g. serve.)")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_slow)

    sp = sub.add_parser(
        "memory", help="cluster object table grouped by callsite")
    sp.add_argument("--address")
    sp.add_argument("--tag", help="filter rows by semantic tag "
                                  "(put/task_return/kv_export/...)")
    sp.add_argument("--json", action="store_true",
                    help="raw row list instead of the grouped table")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser("list")
    sp.add_argument("kind")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("summary")
    sp.add_argument("kind")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("timeline")
    sp.add_argument("--out")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser(
        "job", usage="ray-tpu job submit [--wait] -- <entrypoint...> | "
                     "ray-tpu job status|logs|stop <job_id> | "
                     "ray-tpu job list")
    sp.add_argument("job_cmd",
                    choices=["submit", "status", "logs", "stop", "list"])
    sp.add_argument("--wait", action="store_true")
    sp.add_argument("--address")
    sp.add_argument("job_id", nargs="?")
    sp.add_argument("entrypoint", nargs="*")
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser(
        "stack", help="dump stacks of all live runtime processes")
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser(
        "serve", usage="ray-tpu serve deploy <config.json> | "
                       "ray-tpu serve status | ray-tpu serve shutdown")
    sp.add_argument("serve_cmd", choices=["deploy", "status", "shutdown"])
    sp.add_argument("config_file", nargs="?")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_serve)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
