/* ray-tpu dashboard SPA: tabs over the JSON API (see head.py routes).
   No framework — the environment ships no npm; fetch + innerHTML keep it
   auditable and dependency-free. */
"use strict";

const TABS = ["overview", "nodes", "actors", "tasks", "placement groups",
              "jobs", "serve", "objects", "metrics"];
let current = "overview";
let timer = null;

const $ = (id) => document.getElementById(id);
const esc = (s) => String(s ?? "").replace(/[&<>"]/g,
  (c) => ({"&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;"}[c]));

async function getJSON(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(`${path}: HTTP ${r.status}`);
  return r.json();
}

function table(headers, rows) {
  const h = headers.map((x) => `<th>${esc(x)}</th>`).join("");
  const body = rows.length
    ? rows.map((r) => `<tr>${r.map((c) => `<td>${c}</td>`).join("")}</tr>`)
        .join("")
    : `<tr><td colspan="${headers.length}" class="muted">none</td></tr>`;
  return `<table><tr>${h}</tr>${body}</table>`;
}

const state = (s) => `<span class="${esc(s)}">${esc(s)}</span>`;
const short = (s) => `<span title="${esc(s)}">${esc(String(s).slice(0, 12))}</span>`;
const fmtRes = (r) => esc(Object.entries(r || {})
  .map(([k, v]) => `${k}:${Math.round(v * 100) / 100}`).join(" "));
const fmtBytes = (n) => {
  if (n == null) return "?";
  const units = ["B", "KiB", "MiB", "GiB"];
  let i = 0;
  while (n >= 1024 && i < units.length - 1) { n /= 1024; i++; }
  return `${Math.round(n * 10) / 10}${units[i]}`;
};

const render = {
  async overview() {
    const [nodes, actors, status] = await Promise.all([
      getJSON("/api/v0/nodes"), getJSON("/api/v0/actors"),
      getJSON("/api/cluster_status")]);
    const alive = nodes.filter((n) => n.state === "ALIVE");
    const cards = [
      ["nodes alive", alive.length],
      ["actors alive", actors.filter((a) => a.state === "ALIVE").length],
      ["cpus", fmtCap(alive, "CPU")],
      ["tpus", fmtCap(alive, "TPU")],
    ].map(([k, v]) =>
      `<div class="card"><div class="k">${k}</div><div class="v">${v}</div></div>`
    ).join("");
    return `<div class="cards">${cards}</div>` +
      `<pre>${esc(JSON.stringify(status, null, 2))}</pre>`;
  },
  async nodes() {
    const nodes = await getJSON("/api/v0/nodes");
    return table(["node", "state", "agent", "resources", "available"],
      nodes.map((n) => [short(n.node_id), state(n.state),
                        esc(n.agent_addr || ""), fmtRes(n.resources),
                        fmtRes(n.available)]));
  },
  async actors() {
    const actors = await getJSON("/api/v0/actors");
    return table(["actor", "name", "state", "class", "node", "restarts"],
      actors.map((a) => [short(a.actor_id), esc(a.name || ""),
                         state(a.state), esc(a.class_name || ""),
                         short(a.node_id || ""), esc(a.num_restarts ?? 0)]));
  },
  async tasks() {
    const [summary, tasks] = await Promise.all([
      getJSON("/api/v0/tasks/summarize"), getJSON("/api/v0/tasks?limit=200")]);
    const cards = Object.entries(summary.by_state || summary || {})
      .map(([k, v]) =>
        `<div class="card"><div class="k">${esc(k)}</div><div class="v">${esc(v)}</div></div>`)
      .join("");
    const rows = (Array.isArray(tasks) ? tasks : tasks.tasks || [])
      .slice(-200).reverse().map((t) => [
        short(t.task_id || ""), esc(t.name || ""), state(t.state || ""),
        esc(t.func_or_class_name || ""), short(t.node_id || "")]);
    return `<div class="cards">${cards}</div>` +
      table(["task", "name", "state", "func", "node"], rows);
  },
  async "placement groups"() {
    const pgs = await getJSON("/api/v0/placement_groups");
    return table(["pg", "state", "strategy", "bundles"],
      pgs.map((p) => [short(p.pg_id || p.placement_group_id || ""),
                      state(p.state), esc(p.strategy || ""),
                      esc(JSON.stringify(p.bundles || []))]));
  },
  async jobs() {
    const jobs = await getJSON("/api/jobs/");
    return table(["job", "status", "entrypoint", "start", "end"],
      (Array.isArray(jobs) ? jobs : []).map((j) => [
        short(j.submission_id || j.job_id || ""), state(j.status || ""),
        esc((j.entrypoint || "").slice(0, 80)),
        fmtTime(j.start_time), fmtTime(j.end_time)]));
  },
  async serve() {
    const s = await getJSON("/api/serve/applications/");
    return `<pre>${esc(JSON.stringify(s, null, 2))}</pre>`;
  },
  async objects() {
    const [sum, mem] = await Promise.all([
      getJSON("/api/v0/objects"),
      getJSON("/api/v0/memory?view=rows&limit=500")]);
    const c = sum.result?.cluster || {};
    const leaks = c.leaks || {};
    const cards = [
      ["objects", c.total_objects ?? "?"],
      ["bytes", fmtBytes(c.total_bytes)],
      ["orphan pin bytes", fmtBytes(leaks.arena_orphan_pin_bytes)],
      ["unreachable owner bytes",
       fmtBytes(leaks.objects_unreachable_owner_bytes)],
    ].map(([k, v]) =>
      `<div class="card"><div class="k">${esc(k)}</div><div class="v">${esc(v)}</div></div>`
    ).join("");
    const rows = (mem.result?.objects || []).map((r) => [
      short(r.object_id), fmtBytes(r.size), esc(r.tier), esc(r.tag),
      esc(r.callsite), esc(String(r.owner ?? "UNOWNED")),
      esc(r.pins), `${esc(r.local_refs)}/${esc(r.borrowers)}`,
      esc((r.store_nodes || (r.node ? [r.node] : [])).join(","))]);
    return `<div class="cards">${cards}</div>` +
      table(["object", "size", "tier", "tag", "callsite", "owner",
             "pins", "refs/borrow", "nodes"], rows);
  },
  async metrics() {
    const r = await fetch("/metrics");
    return `<pre>${esc(await r.text())}</pre>`;
  },
};

function fmtCap(nodes, key) {
  const total = nodes.reduce((a, n) => a + (n.resources?.[key] || 0), 0);
  const avail = nodes.reduce((a, n) => a + (n.available?.[key] || 0), 0);
  return total ? `${Math.round((total - avail) * 10) / 10}/${total}` : "0";
}
const fmtTime = (t) => t ? esc(new Date(t * 1000).toLocaleTimeString()) : "";

async function refresh() {
  try {
    $("main").innerHTML = await render[current]();
    $("error").style.display = "none";
    $("refreshed").textContent =
      `updated ${new Date().toLocaleTimeString()}`;
  } catch (e) {
    $("error").textContent = String(e);
    $("error").style.display = "block";
  }
}

function select(tab) {
  current = tab;
  document.querySelectorAll("nav button").forEach((b) =>
    b.classList.toggle("active", b.dataset.tab === tab));
  refresh();
}

window.addEventListener("DOMContentLoaded", () => {
  $("tabs").innerHTML = TABS.map((t) =>
    `<button data-tab="${t}">${t}</button>`).join("");
  document.querySelectorAll("nav button").forEach((b) =>
    b.addEventListener("click", () => select(b.dataset.tab)));
  select("overview");
  timer = setInterval(refresh, 3000);
});
