"""Dashboard: HTTP observability + job REST API for the cluster.

Analog of ray: python/ray/dashboard/ (DashboardHead head.py:79, per-module
aiohttp handlers under dashboard/modules/).  The React frontend is replaced
by a minimal HTML index; the REST surface mirrors the reference's routes so
tooling built against them ports over.
"""
from ray_tpu.dashboard.head import DashboardHead, start_dashboard

__all__ = ["DashboardHead", "start_dashboard"]
