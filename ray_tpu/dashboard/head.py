"""DashboardHead: aiohttp server exposing cluster state over HTTP.

Analog of ray: python/ray/dashboard/head.py:79 (DashboardHead) with the
per-module route handlers of python/ray/dashboard/modules/{node,actor,job,
metrics,state,healthz}.  Runs in-process (thread + private event loop) on
the head node; `ray-tpu start --head` and `ray_tpu.init(dashboard=True)`
launch it.

Routes (reference parity):
  GET  /api/version                   version + session info
  GET  /api/cluster_status            autoscaler-style cluster summary
  GET  /nodes  /api/v0/nodes          node table
  GET  /api/v0/actors                 actor table
  GET  /api/v0/tasks                  task events
  GET  /api/v0/tasks/summarize        counts by (function, state)
  GET  /api/v0/placement_groups       placement groups
  GET  /api/v0/objects                cluster object ledger summary
  GET  /api/v0/memory                 object table + leak sentinel
                                      (?tag=, ?limit=, ?view=rows)
  GET  /api/jobs/                     job list            (ray jobs REST)
  POST /api/jobs/                     submit a job
  GET  /api/jobs/{id}                 job status
  POST /api/jobs/{id}/stop            stop a job
  GET  /api/jobs/{id}/logs            job logs
  GET  /metrics                       Prometheus text exposition
  GET  /api/v0/timeline               Chrome trace JSON
  GET  /api/v0/timeseries             telemetry timeline
                                      (?series=, ?since=, ?fresh=1)
  GET  /api/healthz  /api/gcs_healthz liveness
  GET  /                              minimal HTML summary
"""
from __future__ import annotations

import asyncio
import json
import logging
import threading
import time

logger = logging.getLogger(__name__)

_DEFAULT_PORT = 8265          # same default as the reference dashboard


def _json(data, status: int = 200):
    from aiohttp import web

    return web.Response(text=json.dumps(data), status=status,
                        content_type="application/json")


class DashboardHead:
    """HTTP head service over the controller's state (ray: head.py:79)."""

    def __init__(self, host: str = "127.0.0.1", port: int = _DEFAULT_PORT):
        self.host = host
        self.port = port
        self._thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._runner = None
        self.url = f"http://{host}:{port}"

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "DashboardHead":
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="dashboard-head")
        self._thread.start()
        if not self._started.wait(timeout=15):
            raise RuntimeError("dashboard failed to start")
        return self

    def stop(self) -> None:
        loop = self._loop
        if loop is None:
            return

        async def _close():
            if self._runner is not None:
                await self._runner.cleanup()
            loop.stop()
        try:
            asyncio.run_coroutine_threadsafe(_close(), loop)
            self._thread.join(timeout=5)
        except Exception:  # noqa: BLE001
            pass

    def _serve(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        app = web.Application()
        self._add_routes(app)

        async def _up():
            self._runner = web.AppRunner(app)
            await self._runner.setup()
            site = web.TCPSite(self._runner, self.host, self.port)
            await site.start()
            # Port 0 → bound port discovery for tests.
            for s in self._runner.sites:
                srv = getattr(s, "_server", None)
                if srv and srv.sockets:
                    self.port = srv.sockets[0].getsockname()[1]
            self.url = f"http://{self.host}:{self.port}"
            self._started.set()
        loop.run_until_complete(_up())
        loop.run_forever()

    # -------------------------------------------------------------- routes
    def _add_routes(self, app) -> None:
        from aiohttp import web

        r = app.router
        # Frontend SPA (ray: dashboard/client React build → static files;
        # here a dependency-free vanilla-JS page over the same API).
        r.add_get("/", self._static_index)
        r.add_get("/app.js", self._static_appjs)
        r.add_get("/legacy", self._index)
        r.add_get("/api/version", self._version)
        r.add_get("/api/healthz", self._healthz)
        r.add_get("/api/gcs_healthz", self._healthz)
        r.add_get("/api/cluster_status", self._cluster_status)
        r.add_get("/nodes", self._nodes)
        r.add_get("/api/v0/nodes", self._nodes)
        r.add_get("/api/v0/actors", self._actors)
        r.add_get("/api/v0/tasks", self._tasks)
        r.add_get("/api/v0/tasks/summarize", self._tasks_summarize)
        r.add_get("/api/v0/placement_groups", self._pgs)
        r.add_get("/api/v0/objects", self._objects)
        r.add_get("/api/v0/memory", self._memory)
        r.add_get("/api/v0/timeline", self._timeline)
        r.add_get("/api/v0/timeseries", self._timeseries)
        r.add_get("/api/v0/traces", self._traces)
        r.add_get("/api/v0/worker_messages", self._worker_messages)
        r.add_get("/metrics", self._metrics)
        r.add_get("/api/jobs/", self._jobs_list)
        r.add_post("/api/jobs/", self._jobs_submit)
        r.add_get("/api/jobs/{job_id}", self._jobs_get)
        r.add_post("/api/jobs/{job_id}/stop", self._jobs_stop)
        r.add_get("/api/jobs/{job_id}/logs", self._jobs_logs)
        r.add_get("/api/serve/applications/", self._serve_get)
        r.add_put("/api/serve/applications/", self._serve_apply)
        _ = web  # imported for side effects above

    async def _serve_get(self, _req):
        """Serve app status (ray: dashboard serve agent GET)."""
        def _status():
            from ray_tpu import serve

            try:
                return {"applications": serve.status()}
            except Exception as e:  # noqa: BLE001
                return {"applications": {}, "error": str(e)}
        return _json(await self._call(_status))

    async def _serve_apply(self, req):
        """Declarative config apply (ray: PUT /api/serve/applications/
        with a ServeDeploySchema payload — serve deploy's REST target)."""
        body = await req.json()

        def _apply():
            from ray_tpu.serve.schema import apply_config

            return apply_config(body)
        try:
            routes = await self._call(_apply)
            return _json({"applied": routes})
        except Exception as e:  # noqa: BLE001
            return _json({"error": f"{type(e).__name__}: {e}"}, status=400)

    # Handlers call the (blocking, thread-safe) state API off this loop.
    async def _call(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            None, fn, *args)

    async def _static_index(self, _req):
        return self._static_file("index.html", "text/html")

    async def _static_appjs(self, _req):
        return self._static_file("app.js", "application/javascript")

    def _static_file(self, name: str, ctype: str):
        import os

        from aiohttp import web

        path = os.path.join(os.path.dirname(__file__), "client", name)
        with open(path, encoding="utf-8") as f:
            return web.Response(text=f.read(), content_type=ctype)

    async def _index(self, _req):
        from aiohttp import web

        from ray_tpu.utils import state

        nodes = await self._call(state.list_nodes)
        actors = await self._call(state.list_actors)
        alive = [n for n in nodes if n["state"] == "ALIVE"]
        rows = "".join(
            f"<tr><td>{n['node_id'][:12]}</td><td>{n['state']}</td>"
            f"<td>{n.get('agent_addr', '')}</td>"
            f"<td>{json.dumps(n.get('resources', {}))}</td></tr>"
            for n in nodes)
        html = (
            "<html><head><title>ray-tpu dashboard</title></head><body>"
            f"<h1>ray-tpu</h1><p>{len(alive)} alive node(s), "
            f"{len([a for a in actors if a['state'] == 'ALIVE'])} alive "
            "actor(s)</p>"
            "<table border=1><tr><th>node</th><th>state</th><th>agent</th>"
            f"<th>resources</th></tr>{rows}</table>"
            "<p>REST: /api/v0/nodes /api/v0/actors /api/v0/tasks "
            "/api/jobs/ /metrics /api/v0/timeline</p></body></html>")
        return web.Response(text=html, content_type="text/html")

    async def _version(self, _req):
        import ray_tpu

        return _json({"version": getattr(ray_tpu, "__version__", "0.1.0"),
                      "ray_version": getattr(ray_tpu, "__version__",
                                             "0.1.0"),
                      "session_name": "ray-tpu"})

    async def _healthz(self, _req):
        from aiohttp import web

        try:
            from ray_tpu.utils import state

            await self._call(state.list_nodes)
            return web.Response(text="success")
        except Exception as e:  # noqa: BLE001
            return web.Response(text=f"unhealthy: {e}", status=503)

    async def _cluster_status(self, _req):
        import ray_tpu

        nodes = await self._call(ray_tpu.nodes)
        total = await self._call(ray_tpu.cluster_resources)
        avail = await self._call(ray_tpu.available_resources)
        return _json({
            "data": {
                "clusterStatus": {
                    "loadMetricsReport": {
                        "usage": {
                            k: [total.get(k, 0) - avail.get(k, 0),
                                total.get(k, 0)] for k in total},
                    },
                    "aliveNodes": len([n for n in nodes
                                       if n["state"] == "ALIVE"]),
                }}})

    async def _nodes(self, _req):
        from ray_tpu.utils import state

        return _json({"result": True,
                      "data": {"nodes": await self._call(state.list_nodes)}})

    async def _actors(self, _req):
        from ray_tpu.utils import state

        return _json({"result": await self._call(state.list_actors)})

    async def _tasks(self, req):
        from ray_tpu.utils import state

        limit = int(req.query.get("limit", "1000"))
        return _json({"result": await self._call(state.list_tasks, limit)})

    async def _tasks_summarize(self, _req):
        from ray_tpu.utils import state

        return _json({"result": await self._call(state.summarize_tasks)})

    async def _pgs(self, _req):
        from ray_tpu.utils import state

        return _json({"result":
                      await self._call(state.list_placement_groups)})

    def _harvest_cached(self):
        """One memory-verb fan-out behind a short TTL feeds the objects
        tab's rows, /api/v0/objects and every /metrics scrape — each
        would otherwise fire its own full cluster broadcast
        (controller→agents→workers→drivers, up to ~15s against a
        wedged member)."""
        import time as _time

        from ray_tpu.utils import state

        cached = getattr(self, "_harvest_cache", None)
        now = _time.monotonic()
        if cached is not None and now - cached[0] < 5.0:
            return cached[1]
        harvest = state._harvest_memory(5000, 30.0)
        self._harvest_cache = (now, harvest)
        return harvest

    def _summarize_cached(self):
        from ray_tpu.utils import state

        return state._summarize_from(*self._harvest_cached())

    async def _objects(self, _req):
        """Cluster object ledger summary (was: this process's own
        `core.owned` count — a dashboard watching only itself)."""
        return _json({"result": await self._call(self._summarize_cached)})

    async def _memory(self, req):
        """Object ledger harvest (the `ray memory` table over HTTP).
        ?view=rows returns the per-object table (?tag= filters,
        ?limit= bounds per-process replies); the default is the
        per-callsite grouped summary plus leak-sentinel gauges."""
        view = req.query.get("view", "summary")
        tag = req.query.get("tag") or None
        try:
            limit = int(req.query.get("limit", "5000"))
        except ValueError:
            return _json({"error": "limit must be an integer"},
                         status=400)

        def _collect():
            from ray_tpu.utils import state

            if view == "rows":
                # Same cached harvest as the summary endpoints: the
                # objects tab fetches both in one render.
                procs, agents, _d, _dd = self._harvest_cached()
                rows, _diag = state._merge_object_rows(procs, agents)
                rows.sort(key=lambda r: -r["size"])
                filters = [("tag", "=", tag)] if tag else None
                return {"objects":
                        state._apply_filters(rows, filters)[:limit]}
            return self._summarize_cached()
        return _json({"result": await self._call(_collect)})

    async def _timeline(self, _req):
        import ray_tpu

        events = await self._call(ray_tpu.timeline)
        return _json(events)

    async def _timeseries(self, req):
        """Cluster telemetry timeline (the `telemetry` verb fan-out
        merged head-side).  Query params: ?series= comma-separated
        series-key prefixes (e.g. serve_llm_); ?since= either an
        absolute unix timestamp or, below 1e6, "last N seconds";
        ?fresh=1 forces every process to sample before replying."""
        import time as _time

        from ray_tpu import telemetry

        series = [s for s in
                  (req.query.get("series") or "").split(",") if s] \
            or None
        since_q = req.query.get("since")
        fresh = req.query.get("fresh") in ("1", "true")
        try:
            since = float(since_q) if since_q else None
        except ValueError:
            return _json({"error": "since must be a number"},
                         status=400)
        if since is not None and since < 1e6:
            since = _time.time() - since

        def _collect():
            return telemetry.timeseries(series=series, since=since,
                                        fresh=fresh)
        return _json({"result": await self._call(_collect)})

    async def _traces(self, req):
        """Flight-recorder harvest (cluster-wide `spans` verb fan-out)
        merged by trace_id.  Query params: ?trace_id= filters to one
        request's tree; ?format=chrome|otlp exports the Chrome-trace /
        OTLP document shapes; ?analyze=1 adds the critical-path
        decomposition (per-stage p50/p99 attribution + the N worst
        requests with their blocking chains; ?limit= bounds N;
        ?match= scopes BOTH to traces whose root span name starts with
        the prefix — without it, every task/actor execution roots its
        own trace and control-plane stages drown the serve-request
        percentages, the same failure `ray-tpu slow --match` guards).
        The default reply carries harvest `diagnostics` — per-process
        ring stats whose `dropped` counts mark a wrapped buffer, so a
        partial tree reads as truncated, never as silently complete."""
        from ray_tpu import tracing

        trace_id = req.query.get("trace_id") or None
        fmt = req.query.get("format", "spans")
        analyze = req.query.get("analyze") in ("1", "true")
        match = req.query.get("match") or None
        try:
            limit = int(req.query.get("limit", "10"))
        except ValueError:
            return _json({"error": "limit must be an integer"},
                         status=400)

        def _collect():
            spans_list, diags = tracing.harvest(
                trace_id=trace_id, with_diagnostics=True)
            if fmt == "chrome":
                return tracing.chrome_trace(spans_list)
            if fmt == "otlp":
                return tracing.otlp_document(spans_list)
            trees = tracing.trace_trees(spans_list)
            groups = tracing.traces(spans_list)
            out = {"spans": spans_list,
                   "diagnostics": diags,
                   "traces": {tid: {"roots": len(roots),
                                    "connected": len(roots) == 1,
                                    "spans": len(groups.get(tid, ()))}
                              for tid, roots in trees.items()}}
            if analyze:
                scoped = trees if not match else {
                    tid: roots for tid, roots in trees.items()
                    if len(roots) == 1
                    and roots[0]["span"]["name"].startswith(match)}
                out["analysis"] = {
                    "attribution": tracing.attribution(scoped),
                    "slowest": tracing.slowest(scoped, n=limit,
                                               prefix=match),
                }
            return out
        return _json(await self._call(_collect))

    async def _worker_messages(self, _req):
        """Messages posted via ray_tpu.show_in_dashboard (ray:
        worker.py:2521 → dashboard actor/worker detail panes)."""
        import json as _jsonlib

        from ray_tpu._private.worker import global_worker

        def _collect():
            core = global_worker()
            keys = core.call(core.controller_addr, "kv_keys",
                             {"ns": "dash"}, timeout=10.0)[0]["keys"]
            out = []
            for k in keys:
                reply, blobs = core.call(core.controller_addr, "kv_get",
                                         {"ns": "dash", "key": k},
                                         timeout=10.0)
                if reply.get("found") and blobs:
                    out.append({"key": k,
                                **_jsonlib.loads(bytes(blobs[0]))})
            return out
        return _json({"result": await self._call(_collect)})

    async def _metrics(self, _req):
        """Prometheus text exposition (ray: per-node metrics agent +
        metric_defs.cc; here one endpoint aggregating worker flushes)."""
        from aiohttp import web

        from ray_tpu.utils import state

        lines: list[str] = []
        try:
            snaps = await self._call(state.list_metrics)
        except Exception:  # noqa: BLE001
            snaps = []
        for snap in snaps:
            wid = str(snap.get("worker_id", "?"))[:12]
            for m in snap.get("metrics", []):
                name = "ray_tpu_" + m.get("name", "unnamed")
                mtype = m.get("type", "gauge")
                if mtype == "histogram" and m.get("counts"):
                    # Proper Prometheus histogram family
                    # (_bucket/_sum/_count with a +Inf bucket) — a
                    # collapsed scalar sum is scrape-broken: quantile
                    # queries (histogram_quantile over the new
                    # TTFT/TPOT series) need the cumulative buckets.
                    lines.append(f"# TYPE {name} histogram")
                    bounds = m.get("boundaries", [])
                    sums = {tuple(sorted(v.get("tags", {}).items())):
                            v.get("value", 0)
                            for v in m.get("values", ())}
                    for row in m.get("counts", ()):
                        tags = {**row.get("tags", {}), "worker": wid}
                        base = ",".join(
                            f'{k}="{tv}"' for k, tv in
                            sorted(tags.items()))
                        counts = row.get("counts", [])
                        cum = 0
                        for b, c in zip(bounds, counts):
                            cum += c
                            lines.append(
                                f'{name}_bucket{{{base},le="{b}"}} '
                                f"{cum}")
                        total = sum(counts)
                        lines.append(
                            f'{name}_bucket{{{base},le="+Inf"}} '
                            f"{total}")
                        key = tuple(sorted(row.get("tags", {}).items()))
                        lines.append(
                            f"{name}_sum{{{base}}} "
                            f"{sums.get(key, 0)}")
                        lines.append(f"{name}_count{{{base}}} {total}")
                    continue
                lines.append(f"# TYPE {name} "
                             f"{'counter' if mtype == 'counter' else 'gauge'}")
                for v in m.get("values", ()):
                    tags = {**v.get("tags", {}), "worker": wid}
                    tag_s = ",".join(f'{k}="{tv}"' for k, tv in
                                     sorted(tags.items()))
                    lines.append(f"{name}{{{tag_s}}} {v.get('value', 0)}")
        # Always-on cluster gauges.
        try:
            from ray_tpu.utils import state as st

            nodes = await self._call(st.list_nodes)
            alive = len([n for n in nodes if n["state"] == "ALIVE"])
            lines.append(f"ray_tpu_cluster_alive_nodes {alive}")
        except Exception:  # noqa: BLE001
            pass
        # Leak-sentinel gauges (memory ledger): the test-only
        # "zero leaked pins" invariants as live alarms (TTL-cached —
        # scrapes must not each pay a cluster fan-out).
        try:
            leaks = (await self._call(self._summarize_cached))[
                "cluster"]["leaks"]
            lines.append("ray_tpu_arena_orphan_pin_bytes "
                         f"{leaks['arena_orphan_pin_bytes']}")
            unreach = leaks.get("objects_unreachable_owner_bytes")
            if unreach is not None:
                lines.append("ray_tpu_objects_unreachable_owner_bytes "
                             f"{unreach}")
        except Exception:  # noqa: BLE001
            pass
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    # ------------------------------------------------------------ jobs REST
    async def _jobs_list(self, _req):
        from ray_tpu.job_submission import JobSubmissionClient

        jobs = await self._call(lambda: JobSubmissionClient().list_jobs())
        return _json(jobs)

    async def _jobs_submit(self, req):
        from ray_tpu.job_submission import JobSubmissionClient

        body = await req.json()
        entrypoint = body.get("entrypoint")
        if not entrypoint:
            return _json({"error": "entrypoint required"}, status=400)

        def _submit():
            cli = JobSubmissionClient()
            return cli.submit_job(
                entrypoint=entrypoint,
                job_id=body.get("job_id") or body.get("submission_id"),
                runtime_env=body.get("runtime_env"),
                metadata=body.get("metadata"))
        try:
            job_id = await self._call(_submit)
        except Exception as e:  # noqa: BLE001
            return _json({"error": str(e)}, status=500)
        return _json({"job_id": job_id, "submission_id": job_id})

    async def _jobs_get(self, req):
        from ray_tpu.job_submission import JobSubmissionClient

        jid = req.match_info["job_id"]
        try:
            info = await self._call(
                lambda: JobSubmissionClient().get_job_info(jid))
        except Exception as e:  # noqa: BLE001
            return _json({"error": str(e)}, status=404)
        return _json(info)

    async def _jobs_stop(self, req):
        from ray_tpu.job_submission import JobSubmissionClient

        jid = req.match_info["job_id"]
        stopped = await self._call(
            lambda: JobSubmissionClient().stop_job(jid))
        return _json({"stopped": bool(stopped)})

    async def _jobs_logs(self, req):
        from ray_tpu.job_submission import JobSubmissionClient

        jid = req.match_info["job_id"]
        try:
            logs = await self._call(
                lambda: JobSubmissionClient().get_job_logs(jid))
        except Exception as e:  # noqa: BLE001
            return _json({"error": str(e)}, status=404)
        return _json({"logs": logs})


def start_dashboard(host: str = "127.0.0.1",
                    port: int = _DEFAULT_PORT) -> DashboardHead:
    """Start the dashboard against the already-initialized runtime."""
    head = DashboardHead(host, port)
    return head.start()
