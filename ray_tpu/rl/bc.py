"""BC: behavior cloning from offline experience (the offline-RL entry).

Analog of ray: rllib/algorithms/bc/ (BC / BCConfig over rllib/offline/
data readers) — supervised policy learning from logged (obs, action)
pairs, no environment interaction during training.  Offline batches ride
ray_tpu.data Datasets (the reference reads offline JSON/Parquet through
Ray Data the same way).
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.num_env_runners = 0        # offline: no sampling actors
        self.offline_data = None        # ray_tpu.data.Dataset | dict
        self.eval_episodes = 2          # rollouts per step() for metrics

    def offline(self, offline_data=None, **_kw) -> "BCConfig":
        if offline_data is not None:
            self.offline_data = offline_data
        return self


class BC(Algorithm):
    # Offline columns the loss consumes (MARWIL adds "returns").
    _offline_keys: tuple = ("obs", "actions")

    @staticmethod
    def loss_builder(config: dict):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl import models

        def loss_fn(params, batch):
            logits = models.policy_logits(params, batch["obs"], jnp)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(
                logp, batch["actions"][:, None], axis=-1)[:, 0]
            loss = jnp.mean(nll)
            acc = jnp.mean(
                (jnp.argmax(logits, axis=-1) == batch["actions"])
                .astype(jnp.float32))
            return loss, {"bc_loss": loss, "action_accuracy": acc}
        return loss_fn

    def setup(self, config: dict) -> None:
        config = dict(config or {})
        offline = config.pop("offline_data", None)
        if offline is None:
            raise ValueError("BC requires offline_data "
                             "(config.offline(offline_data=...))")
        from ray_tpu.rl.algorithm import coerce_offline

        batch = coerce_offline(offline, type(self)._offline_keys)
        # Default ONE eval runner when eval is on (none when off), but an
        # explicit .env_runners() choice wins.
        cfg_eval = dict(config)
        if "num_env_runners" not in config or \
                config.get("num_env_runners", 0) == 0:
            cfg_eval["num_env_runners"] = \
                1 if config.get("eval_episodes", 2) > 0 else 0
        super().setup(cfg_eval)
        # Ship the offline batch to the object store ONCE; updates pass
        # the ref, not the arrays, and the driver keeps no second copy
        # (ray: offline data rides the object store).
        import ray_tpu

        self._offline_ref = ray_tpu.put(batch)
        self._n_offline = len(batch["obs"])

    def training_step(self) -> dict:
        metrics = self.learner_group.update(
            self._offline_ref,
            num_sgd_iter=self.cfg["num_sgd_iter"],
            minibatch_size=self.cfg["minibatch_size"])
        self._params_np = self.learner_group.get_params_numpy()
        self._timesteps += self._n_offline
        self._greedy_eval(self.cfg.get("eval_episodes", 2))
        return metrics


BC._default_config = BCConfig()
BCConfig.algo_class = BC
