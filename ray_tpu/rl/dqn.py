"""DQN: off-policy Q-learning with replay + target network.

Analog of ray: rllib/algorithms/dqn/ (DQN, DQNConfig; double-DQN loss in
dqn_torch_learner/dqn_rainbow_learner).  The "pi" head doubles as the
Q-network (argmax action selection on env runners via epsilon-greedy).
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.replay import ReplayBuffer


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.replay_capacity = 50_000
        self.learning_starts = 1_000
        self.target_update_freq = 500       # env steps between target syncs
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 10_000
        self.train_batch_size = 256         # sampled per iteration
        self.sgd_batch_size = 64

    def training(self, *, replay_capacity=None, learning_starts=None,
                 target_update_freq=None, epsilon_decay_steps=None,
                 sgd_batch_size=None, **kw) -> "DQNConfig":
        for name, v in [("replay_capacity", replay_capacity),
                        ("learning_starts", learning_starts),
                        ("target_update_freq", target_update_freq),
                        ("epsilon_decay_steps", epsilon_decay_steps),
                        ("sgd_batch_size", sgd_batch_size)]:
            if v is not None:
                setattr(self, name, v)
        super().training(**kw)
        return self


class DQN(Algorithm):
    @staticmethod
    def loss_builder(config: dict):
        import jax.numpy as jnp

        from ray_tpu.rl import models

        gamma = config.get("gamma", 0.99)

        def loss_fn(params, batch):
            q = models.policy_logits(params, batch["obs"], jnp)
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=-1)[:, 0]
            # Double DQN: online net picks, target net evaluates
            # (target Q values are computed outside and shipped in batch).
            target = batch["q_targets"]
            loss = jnp.mean((q_taken - target) ** 2)
            return loss, {"q_mean": jnp.mean(q_taken),
                          "td_error": jnp.mean(jnp.abs(q_taken - target))}
        return loss_fn

    def setup(self, config: dict) -> None:
        super().setup(config)
        self.replay = ReplayBuffer(self.cfg["replay_capacity"],
                                   seed=self.cfg["seed"])
        self._target_params = self._params_np
        self._last_target_sync = 0

    def _epsilon(self) -> float:
        frac = min(1.0, self._timesteps / self.cfg["epsilon_decay_steps"])
        return self.cfg["epsilon_initial"] + frac * (
            self.cfg["epsilon_final"] - self.cfg["epsilon_initial"])

    def training_step(self) -> dict:
        from ray_tpu.rl import models

        batch = self._collect(epsilon=self._epsilon())
        self.replay.add_batch(batch)
        if len(self.replay) < self.cfg["learning_starts"]:
            return {"buffer_size": float(len(self.replay))}
        metrics = {}
        for _ in range(4):
            sample = self.replay.sample(self.cfg["sgd_batch_size"])
            # Double-DQN targets with the frozen target net (numpy).
            q_next_online = models.policy_logits(self._params_np,
                                                 sample["next_obs"])
            best = np.argmax(q_next_online, axis=-1)
            q_next_target = models.policy_logits(self._target_params,
                                                 sample["next_obs"])
            q_sel = q_next_target[np.arange(len(best)), best]
            sample["q_targets"] = (
                sample["rewards"] + self.cfg["gamma"] *
                (1.0 - sample["dones"]) * q_sel).astype(np.float32)
            metrics = self.learner_group.update(sample, num_sgd_iter=1)
        self._params_np = self.learner_group.get_params_numpy()
        if self._timesteps - self._last_target_sync >= \
                self.cfg["target_update_freq"]:
            self._target_params = self._params_np
            self._last_target_sync = self._timesteps
        metrics["epsilon"] = self._epsilon()
        return metrics


DQN._default_config = DQNConfig()
DQNConfig.algo_class = DQN
