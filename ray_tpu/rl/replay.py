"""Replay buffers for off-policy algorithms.

Analog of ray: rllib/utils/replay_buffers/ (EpisodeReplayBuffer /
MultiAgentReplayBuffer) — a flat uniform-sampling transition buffer.
"""
from __future__ import annotations

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int = 50_000, seed: int = 0):
        self.capacity = capacity
        self.rng = np.random.default_rng(seed)
        self._storage: dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0

    def add_batch(self, batch: dict) -> None:
        n = len(batch["obs"])
        if not self._storage:
            # Schema follows the first batch (algorithms differ: DQN/SAC
            # store next_obs, DreamerV3 stores sequence flags instead).
            for k, v in batch.items():
                v = np.asarray(v)
                shape = (self.capacity,) + tuple(v.shape[1:])
                self._storage[k] = np.zeros(shape, v.dtype)
        for i in range(n):
            j = self._next
            for k, arr in self._storage.items():
                arr[j] = batch[k][i]
            self._next = (self._next + 1) % self.capacity
            self._size = min(self._size + 1, self.capacity)

    def sample(self, batch_size: int) -> dict:
        idx = self.rng.integers(0, self._size, size=batch_size)
        return {k: arr[idx] for k, arr in self._storage.items()}

    def storage(self) -> dict:
        """Time-ordered view of the live region (sequence samplers slice
        contiguous windows from this; valid until the ring wraps)."""
        return {k: arr[:self._size] for k, arr in self._storage.items()}

    def __len__(self) -> int:
        return self._size
