"""APPO: asynchronous PPO on the IMPALA actor-learner trunk.

Analog of ray: rllib/algorithms/appo/appo.py:277 (APPO / APPOConfig) and
appo_torch_learner.py — the clipped PPO surrogate driven by V-trace
corrected advantages, with a target network (polyak-synced inside the
jitted update, like SAC's) supplying the KL anchor: the learner keeps
updating while env runners sample with stale params, and the KL term
keeps the online policy from racing away from the one that collected
the data.

TPU shape: same one-XLA-program update as IMPALA (V-trace recursion is
a lax.scan); the target sync is composed into the compiled step via the
learner's post_update hook rather than a separate torch-style
update_target() call.
"""
from __future__ import annotations

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.impala import IMPALA, IMPALAConfig, vtrace_returns


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.4            # rllib appo.py default
        self.use_kl_loss = True
        self.kl_coeff = 0.2
        self.tau = 0.05                  # polyak rate of the target net
        self.num_sgd_iter = 1

    def training(self, *, clip_param=None, use_kl_loss=None,
                 kl_coeff=None, tau=None, **kw) -> "APPOConfig":
        for name, v in [("clip_param", clip_param),
                        ("use_kl_loss", use_kl_loss),
                        ("kl_coeff", kl_coeff), ("tau", tau)]:
            if v is not None:
                setattr(self, name, v)
        super().training(**kw)
        return self


def appo_params_init(rng, obs_dim: int, n_actions: int,
                     hidden: int = 64) -> dict:
    """Online pi/vf + target copies (flat tree so the env runners'
    models.policy_logits(params) finds "pi" unchanged)."""
    from ray_tpu.rl import models

    p = models.policy_value_init(rng, obs_dim, n_actions, hidden=hidden)
    return {"pi": p["pi"], "vf": p["vf"],
            "pi_t": {k: v for k, v in p["pi"].items()},
            "vf_t": {k: v for k, v in p["vf"].items()}}


def appo_post_update(config: dict):
    """Polyak target sync fused into the jitted update step (rllib:
    APPO target_network_update_freq; SAC-style tau here)."""
    tau = config.get("tau", 0.05)

    def post(params):
        import jax

        new_pi_t = jax.tree.map(lambda o, t: tau * o + (1 - tau) * t,
                                params["pi"], params["pi_t"])
        new_vf_t = jax.tree.map(lambda o, t: tau * o + (1 - tau) * t,
                                params["vf"], params["vf_t"])
        return {**params, "pi_t": new_pi_t, "vf_t": new_vf_t}

    return post


class APPO(IMPALA):
    @staticmethod
    def loss_builder(config: dict):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl import models

        gamma = config.get("gamma", 0.99)
        rho_bar = config.get("vtrace_clip_rho", 1.0)
        pg_rho_bar = config.get("vtrace_clip_pg_rho", 1.0)
        lam = config.get("vtrace_lambda", 1.0)
        vf_coeff = config.get("vf_loss_coeff", 0.5)
        ent_coeff = config.get("entropy_coeff", 0.01)
        clip = config.get("clip_param", 0.4)
        use_kl = config.get("use_kl_loss", True)
        kl_coeff = config.get("kl_coeff", 0.2)

        def loss_fn(params, batch):
            obs = batch["obs"]                      # [B,T,obs]
            B, T = obs.shape[:2]
            flat = lambda a: a.reshape((B * T,) + a.shape[2:])  # noqa: E731
            logits = models.policy_logits(params, flat(obs), jnp)
            logp_all = jax.nn.log_softmax(logits, axis=-1)
            actions = flat(batch["actions"])
            logp = jnp.take_along_axis(
                logp_all, actions[:, None], axis=-1)[:, 0].reshape(B, T)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))

            values = models.value(params, flat(obs), jnp).reshape(B, T)
            v_next = models.value(
                params, flat(batch["next_obs"]), jnp).reshape(B, T)

            # Importance ratios vs the BEHAVIOUR policy that sampled.
            rhos = jnp.exp(logp - batch["logp"])
            vs, pg_adv = vtrace_returns(
                jax, jnp, batch, values, v_next,
                jax.lax.stop_gradient(rhos), gamma, rho_bar, pg_rho_bar,
                lam)
            adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

            # Clipped PPO surrogate on the V-trace advantages
            # (appo_torch_learner.py).
            surrogate = jnp.minimum(
                rhos * adv, jnp.clip(rhos, 1.0 - clip, 1.0 + clip) * adv)
            pi_loss = -jnp.mean(surrogate)
            vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
            total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy

            # KL(target || online): anchors the update to the slow net.
            target_logp_all = jax.nn.log_softmax(models.mlp_apply(
                params["pi_t"], flat(obs), jnp), axis=-1)
            kl = jnp.mean(jnp.sum(
                jnp.exp(target_logp_all) * (target_logp_all - logp_all),
                axis=-1))
            if use_kl:
                total = total + kl_coeff * kl
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy, "mean_kl": kl,
                           "mean_rho": jnp.mean(rhos)}
        return loss_fn

    def setup(self, config: dict) -> None:
        config = dict(config or {})
        config.setdefault("params_builder", appo_params_init)
        config.setdefault("post_update_builder", appo_post_update)
        super().setup(config)


APPO._default_config = APPOConfig()
APPOConfig.algo_class = APPO
