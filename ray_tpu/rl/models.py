"""Policy/value networks as pure functions over param pytrees.

Analog of the reference's RLModule (ray: rllib/core/rl_module/) — the
jax-native shape: params are a dict pytree, `apply` is a pure function
jittable on the learner (TPU) and runnable with numpy on CPU env-runners
(same code path, different array module — no torch-style module objects).
"""
from __future__ import annotations

import numpy as np


def mlp_init(rng, sizes: list[int]) -> dict:
    """He-init MLP params as a dict pytree."""
    import jax

    params = {}
    keys = jax.random.split(rng, len(sizes) - 1)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        import jax.numpy as jnp

        w = jax.random.normal(keys[i], (fan_in, fan_out),
                              jnp.float32) * np.sqrt(2.0 / fan_in)
        params[f"w{i}"] = w
        params[f"b{i}"] = jnp.zeros((fan_out,), jnp.float32)
    return params


def mlp_apply(params: dict, x, xp=np):
    """Forward pass; `xp` = numpy (env runners) or jax.numpy (learner)."""
    n_layers = len(params) // 2
    h = x
    for i in range(n_layers):
        h = h @ params[f"w{i}"] + params[f"b{i}"]
        if i < n_layers - 1:
            h = xp.tanh(h)
    return h


def policy_value_init(rng, obs_dim: int, n_actions: int,
                      hidden: int = 64) -> dict:
    """Separate policy and value MLPs (rllib default fcnet)."""
    import jax

    k1, k2 = jax.random.split(rng)
    return {
        "pi": mlp_init(k1, [obs_dim, hidden, hidden, n_actions]),
        "vf": mlp_init(k2, [obs_dim, hidden, hidden, 1]),
    }


def policy_logits(params: dict, obs, xp=np):
    return mlp_apply(params["pi"], obs, xp)


def value(params: dict, obs, xp=np):
    return mlp_apply(params["vf"], obs, xp)[..., 0]


def to_numpy(params) -> dict:
    """Device → host copy for shipping to env runners."""
    import jax

    return jax.tree.map(lambda a: np.asarray(a), params)


def sample_action(logits: np.ndarray, rng: np.random.Generator) -> tuple:
    """Categorical sample + log-prob (numpy, env-runner side)."""
    z = logits - logits.max(axis=-1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=-1, keepdims=True)
    if logits.ndim == 1:
        a = rng.choice(len(p), p=p)
        return int(a), float(np.log(p[a] + 1e-8))
    acts = np.array([rng.choice(p.shape[-1], p=row) for row in p])
    logp = np.log(p[np.arange(len(acts)), acts] + 1e-8)
    return acts, logp
