"""CQL: conservative Q-learning — offline RL on logged transitions.

Analog of ray: rllib/algorithms/cql/ (CQL / CQLConfig, the SAC-derived
offline algorithm: torch losses in cql_torch_policy.py add the
conservative regularizer min_q_weight * (E_pi[logsumexp Q] - E_D[Q])).
Discrete variant here: the log-sum-exp over the categorical action
support is exact, no sampled actions needed.

Training is fully offline (no env interaction); greedy eval rollouts
measure the learned policy like BC does.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rl.sac import SAC, SACConfig, sac_post_update, sac_params_init


class CQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 0        # offline: no sampling actors
        self.offline_data = None        # dataset | column dict
        self.cql_alpha = 1.0            # conservative-penalty weight
        self.eval_episodes = 2
        self.updates_per_step = 8

    def offline(self, offline_data=None, **_kw) -> "CQLConfig":
        if offline_data is not None:
            self.offline_data = offline_data
        return self

    def training(self, *, cql_alpha=None, **kw) -> "CQLConfig":
        if cql_alpha is not None:
            self.cql_alpha = cql_alpha
        super().training(**kw)
        return self


class CQL(SAC):
    @staticmethod
    def loss_builder(config: dict):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl import models

        sac_loss = SAC.loss_builder(config)
        cql_alpha = config.get("cql_alpha", 1.0)

        def loss_fn(params, batch):
            total, metrics = sac_loss(params, batch)
            # Conservative term per critic: push down out-of-distribution
            # action values (logsumexp over ALL actions) while pushing up
            # the logged actions' values.
            a = batch["actions"][:, None]
            penalty = 0.0
            for qname in ("q1", "q2"):
                q = models.mlp_apply(params[qname], batch["obs"], jnp)
                lse = jax.scipy.special.logsumexp(q, axis=-1)
                q_data = jnp.take_along_axis(q, a, axis=-1)[:, 0]
                penalty = penalty + jnp.mean(lse - q_data)
            total = total + cql_alpha * penalty
            metrics["cql_penalty"] = penalty
            return total, metrics

        return loss_fn

    def setup(self, config: dict) -> None:
        config = dict(config or {})
        offline = config.pop("offline_data", None)
        if offline is None:
            raise ValueError("CQL requires offline_data "
                             "(config.offline(offline_data=...))")
        from ray_tpu.rl.algorithm import coerce_offline

        self._offline = coerce_offline(
            offline, ("obs", "actions", "rewards", "next_obs", "dones"))
        config.setdefault("params_builder", sac_params_init)
        config.setdefault("post_update_builder", sac_post_update)
        # One eval runner for greedy rollouts unless explicitly set.
        if config.get("num_env_runners", 0) == 0 and \
                config.get("eval_episodes", 2) > 0:
            config["num_env_runners"] = 1
        from ray_tpu.rl.algorithm import Algorithm

        Algorithm.setup(self, config)
        self._rng = np.random.default_rng(self.cfg["seed"])
        self._n_offline = len(self._offline["obs"])

    def training_step(self) -> dict:
        metrics: dict = {}
        bs = self.cfg["sgd_batch_size"]
        for _ in range(self.cfg.get("updates_per_step", 8)):
            idx = self._rng.integers(0, self._n_offline, bs)
            sample = {k: v[idx] for k, v in self._offline.items()}
            metrics = self.learner_group.update(sample, num_sgd_iter=1)
        self._params_np = self.learner_group.get_params_numpy()
        self._timesteps += bs * self.cfg.get("updates_per_step", 8)
        self._greedy_eval(self.cfg.get("eval_episodes", 2))
        return metrics


CQL._default_config = CQLConfig()
CQLConfig.algo_class = CQL
