"""PPO: clipped-surrogate policy gradient (the reference's flagship algo).

Analog of ray: rllib/algorithms/ppo/ (PPO, PPOConfig; torch loss in
ppo_torch_learner.py) — jax loss jitted on the learner.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.gae_lambda = 0.95

    def training(self, *, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, gae_lambda=None, **kw) -> "PPOConfig":
        for name, v in [("clip_param", clip_param),
                        ("vf_loss_coeff", vf_loss_coeff),
                        ("entropy_coeff", entropy_coeff),
                        ("gae_lambda", gae_lambda)]:
            if v is not None:
                setattr(self, name, v)
        super().training(**kw)
        return self


class PPO(Algorithm):
    @staticmethod
    def loss_builder(config: dict):
        import jax.numpy as jnp

        from ray_tpu.rl import models

        clip = config.get("clip_param", 0.2)
        vf_coeff = config.get("vf_loss_coeff", 0.5)
        ent_coeff = config.get("entropy_coeff", 0.01)

        def loss_fn(params, batch):
            logits = models.policy_logits(params, batch["obs"], jnp)
            logp_all = logits - jnp.max(logits, axis=-1, keepdims=True)
            logp_all = logp_all - jnp.log(
                jnp.sum(jnp.exp(logp_all), axis=-1, keepdims=True))
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=-1)[:, 0]
            ratio = jnp.exp(logp - batch["logp"])
            adv = batch["advantages"]
            surrogate = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
            pi_loss = -jnp.mean(surrogate)
            v = models.value(params, batch["obs"], jnp)
            vf_loss = jnp.mean((v - batch["value_targets"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "mean_kl": jnp.mean(batch["logp"] - logp)}
        return loss_fn

    def training_step(self) -> dict:
        batch = self._collect()
        metrics = self.learner_group.update(
            batch, num_sgd_iter=self.cfg["num_sgd_iter"],
            minibatch_size=self.cfg["minibatch_size"])
        self._params_np = self.learner_group.get_params_numpy()
        return metrics


PPO._default_config = PPOConfig()
PPOConfig.algo_class = PPO
