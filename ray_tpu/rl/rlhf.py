"""Online GRPO-style RLHF: serve-engine rollouts → TPU learner → live
weight broadcast (ROADMAP item 5, the scenario-diversity flagship — one
workload exercising serve, rl, the collectives, and the object plane).

The loop
--------
1. **Rollout** — `LLMRolloutWorker`s (rl/rollout_llm.py) generate K
   completions per prompt through the paged-KV serve engine; the radix
   prefix cache makes a GRPO group cost ~one prompt prefill.
   Trajectories (token ids, behavior logprobs, rewards) come back as
   object-plane refs the trainer hands straight to the learner.
2. **Update** — `GRPOLearner` computes group-relative advantages
   (reward standardized within each K-completion group — no value
   network) and one clipped-surrogate policy update, jitted; params
   follow the logical-axis sharding rules through the model's own
   constraints, so the same update runs single-device (tests) or
   sharded (a real mesh).  Learner RNG is `fold_in(base, version)` —
   never global numpy state — so runs are bit-reproducible.
3. **Sync** — fresh weights broadcast to every generation engine via
   the ring collectives' `broadcast_pytree` (ONE packed transport) and
   land through `LLMEngine.update_weights`: the engine swaps trees
   BETWEEN decode sync windows, so decode never drains or pauses.
   Staleness is bounded: generation never lags the learner by more
   than `max_weight_lag` versions (the trainer forces a sync first).

Failure model (chaos-tested, tests/test_rlhf_chaos.py)
------------------------------------------------------
- A dying rollout actor (`rl.rollout_step` crash) loses only its
  in-flight group: the trainer respawns the worker, pushes the current
  weights, and regenerates the group (prefix cache makes the retry
  cheap on survivors).
- A dying learner (`rl.weight_sync` crash) resumes from the newest
  COMPLETED async checkpoint (train.checkpoint's background writer);
  parked broadcast waiters are drained via
  `destroy_collective_group(reason)` and the group re-forms at the
  next epoch, exactly like elastic training's membership epochs.

Kill switches: RAY_TPU_RL_WEIGHT_SYNC=0 freezes the serving policy
(generation keeps running on the last synced weights — the same-run
frozen-policy A/B); per-trainer `sync_every=0` never broadcasts.

Layering: core primitives + public facades only (collective,
serve-engine surface, ray_tpu.failpoints, train.checkpoint) — enforced
by tests/test_layering.py.
"""
from __future__ import annotations

import dataclasses
import os
import time
import uuid
from typing import Any, Callable

import numpy as np

import ray_tpu


@dataclasses.dataclass
class RLHFConfig:
    """Knobs for the online loop (picklable: ships to learner/rollout
    actors whole)."""
    model: Any = "debug"            # llama_configs name or LlamaConfig
    params: Any = None              # explicit init params (tests)
    seed: int = 0
    # Prompt pool (synthetic, seeded): n_prompts of prompt_len tokens.
    n_prompts: int = 8
    prompt_len: int = 12
    # GRPO shape.
    group_size: int = 4
    prompts_per_step: int = 2
    max_new_tokens: int = 8
    temperature: float = 1.0
    eos_id: int | None = None
    # Learner.
    lr: float = 1e-3
    clip: float = 0.2
    kl_coeff: float = 0.0
    adv_eps: float = 1e-4
    minibatch_size: int | None = None
    # Topology: 0 rollout workers = everything in-process (bench/unit
    # tests, bit-deterministic); >0 = ray_tpu actors + collective
    # broadcast.  remote_learner puts the learner in its own actor
    # (required for learner-crash recovery to be survivable).
    num_rollout_workers: int = 0
    remote_learner: bool = False
    # Weight sync: broadcast every `sync_every` updates (0 = never);
    # generation may lag the learner by at most `max_weight_lag`
    # versions before the trainer forces a sync.
    sync_every: int = 1
    max_weight_lag: int = 1
    # Async checkpoints every N updates (0 = off) under checkpoint_dir.
    checkpoint_every: int = 0
    checkpoint_dir: str | None = None
    # Engine kwargs for rollout workers (page_size, kv_pages, ...).
    engine: dict = dataclasses.field(default_factory=dict)
    # Reward: "near_token" | "target_token" | callable(prompt, completion).
    reward: Any = "near_token"
    target_token: int | None = None
    rollout_retries: int = 2        # regen attempts per dead rollout
    # Extension points: custom rollout-worker / learner classes (same
    # constructor contracts as LLMRolloutWorker / GRPOLearner).  Used
    # for custom generation stacks — and by the chaos suites to plant
    # failpoint-arming hooks inside specific actors.
    worker_cls: Any = None
    learner_cls: Any = None
    name: str = "rlhf"


def _to_config(config, overrides) -> RLHFConfig:
    if config is None:
        cfg = RLHFConfig()
    elif isinstance(config, RLHFConfig):
        cfg = dataclasses.replace(config)
    else:
        cfg = RLHFConfig(**dict(config))
    for k, v in (overrides or {}).items():
        if not hasattr(cfg, k):
            raise ValueError(f"unknown RLHF config field {k!r}")
        setattr(cfg, k, v)
    return cfg


def _model_config(cfg: RLHFConfig):
    from ray_tpu.models import llama

    return llama.llama_configs()[cfg.model] \
        if isinstance(cfg.model, str) else cfg.model


def _reward_fn(cfg: RLHFConfig) -> Callable:
    from ray_tpu.rl import rollout_llm

    mcfg = _model_config(cfg)
    target = cfg.target_token if cfg.target_token is not None \
        else mcfg.vocab_size // 3
    if callable(cfg.reward):
        return cfg.reward
    if cfg.reward == "near_token":
        return rollout_llm.near_token_reward(target, mcfg.vocab_size)
    if cfg.reward == "target_token":
        return rollout_llm.target_token_reward(target)
    raise ValueError(
        f"unknown reward {cfg.reward!r}; valid: 'near_token', "
        "'target_token', or a callable(prompt, completion)")


def group_advantages(rewards, group_size: int, eps: float = 1e-4):
    """Group-relative advantages (the GRPO estimator, no value
    network): standardize each K-completion group's rewards to zero
    mean/unit std.  A degenerate group (all rewards equal) contributes
    zero advantage — eps keeps it finite, not resurrected.  Works
    jitted (jnp) and eagerly (numpy)."""
    import jax.numpy as jnp

    r = jnp.asarray(rewards, jnp.float32)
    g = r.reshape(-1, group_size)
    mean = g.mean(axis=1, keepdims=True)
    std = g.std(axis=1, keepdims=True)
    return ((g - mean) / (std + eps)).reshape(-1)


def _concat_trajs(trajs: list[dict]) -> dict:
    """Stack worker trajectory batches into one learner batch, padding
    to the widest T (all are pow2-padded already, so this is a max)."""
    T = max(t["tokens"].shape[1] for t in trajs)

    def padded(key, width):
        out = []
        for t in trajs:
            a = np.asarray(t[key])
            if a.shape[1] < width:
                a = np.pad(a, ((0, 0), (0, width - a.shape[1])))
            out.append(a)
        return np.concatenate(out, axis=0)

    return {
        "tokens": padded("tokens", T).astype(np.int32),
        "logprobs": padded("logprobs", T - 1).astype(np.float32),
        "mask": padded("mask", T - 1).astype(np.float32),
        "rewards": np.concatenate(
            [np.asarray(t["rewards"], np.float32) for t in trajs]),
        "group_size": trajs[0]["group_size"],
        "rollout_tokens": int(sum(t["rollout_tokens"] for t in trajs)),
        "weight_version": min(int(t["weight_version"]) for t in trajs),
    }


class GRPOLearner:
    """Jitted GRPO policy update over llama params.

    Runs in-process or as a `ray_tpu.remote` actor (all state
    reconstructible from config + checkpoints).  The update consumes a
    trajectory batch and returns metrics INCLUDING the advantages
    (numpy) — the determinism tests hash them bit-for-bit.

    `mesh` (optional) shards params by the logical-axis rules
    (parallel.sharding.shard_params over llama.param_logical_axes);
    the jitted update then runs under GSPMD with the model's own
    sharding constraints.  Single-device (CPU tests) when None."""

    def __init__(self, config=None, params: Any = None, mesh=None,
                 **overrides):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models import llama

        cfg = _to_config(config, overrides)
        self.cfg = cfg
        self.mcfg = _model_config(cfg)
        self.params = params if params is not None else (
            cfg.params if cfg.params is not None else llama.init_params(
                jax.random.PRNGKey(cfg.seed), self.mcfg))
        if mesh is not None:
            from ray_tpu.parallel.sharding import shard_params

            self.params = shard_params(
                self.params, llama.param_logical_axes(self.mcfg), mesh)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self.version = 0
        # fold_in-derived keys only (RL test discipline: global numpy
        # state would break cross-process reproducibility).
        self._base_key = jax.random.PRNGKey(cfg.seed + 101)
        self._pending_ckpt = None       # (version, path, Checkpoint)
        self._adv = jax.jit(
            lambda r: group_advantages(r, cfg.group_size, cfg.adv_eps))

        clip, klc = cfg.clip, cfg.kl_coeff
        mcfg = self.mcfg

        def _update(params, opt_state, tokens, mask, blogp, adv):
            def loss_fn(p):
                lp = llama.token_logprobs(p, tokens, mcfg)  # [B, T-1]
                ratio = jnp.exp(lp - blogp)
                a = adv[:, None]
                per = jnp.minimum(
                    ratio * a,
                    jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * a)
                denom = jnp.maximum(mask.sum(), 1.0)
                pi_loss = -(per * mask).sum() / denom
                # k1 KL estimate vs the behavior policy (bounds the
                # off-policy drift live sync introduces).
                kl = ((blogp - lp) * mask).sum() / denom
                return pi_loss + klc * kl, (pi_loss, kl,
                                            (ratio * mask).sum() / denom)

            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(_update)

    # ----------------------------------------------------------- update
    def update(self, trajs) -> dict:
        """One GRPO update over a list of trajectory batches (or refs —
        a bare ObjectRef argument resolves before dispatch on the actor
        path, and we resolve explicitly for the in-process path)."""
        import jax.numpy as jnp

        from ray_tpu.object_ref import ObjectRef

        trajs = [ray_tpu.get(t) if isinstance(t, ObjectRef) else t
                 for t in (trajs if isinstance(trajs, (list, tuple))
                           else [trajs])]
        batch = _concat_trajs(trajs)
        B = batch["tokens"].shape[0]
        K = batch["group_size"]
        if B % K:
            raise ValueError(
                f"batch rows {B} not a multiple of group_size {K} — "
                "trajectory groups arrived truncated")
        adv_all = np.asarray(self._adv(batch["rewards"]))
        mb = self.cfg.minibatch_size or B
        idx_order = np.arange(B)
        if mb < B:
            import jax

            # Deterministic shuffle: fold_in(base, version) — the RL
            # seeding discipline (no global numpy RNG).
            idx_order = np.asarray(jax.random.permutation(
                jax.random.fold_in(self._base_key, self.version), B))
        loss = pi_loss = kl = ratio = 0.0
        n_mb = 0
        for s in range(0, B, mb):
            idx = idx_order[s:s + mb]
            self.params, self.opt_state, l, aux = self._update(
                self.params, self.opt_state,
                jnp.asarray(batch["tokens"][idx]),
                jnp.asarray(batch["mask"][idx]),
                jnp.asarray(batch["logprobs"][idx]),
                jnp.asarray(adv_all[idx]))
            loss, (pi_loss, kl, ratio) = float(l), [float(x)
                                                   for x in aux]
            n_mb += 1
        self.version += 1
        return {
            "version": self.version,
            "loss": loss, "policy_loss": pi_loss, "kl": kl,
            "ratio_mean": ratio,
            "reward_mean": float(batch["rewards"].mean()),
            "reward_std": float(batch["rewards"].std()),
            "advantages": adv_all,
            "rollout_tokens": batch["rollout_tokens"],
            "batch_weight_version": batch["weight_version"],
            "minibatches": n_mb,
        }

    # ---------------------------------------------------- weight export
    def broadcast_weights(self, group_name: str,
                          src_rank: int = 0) -> int:
        """Rank-0 side of the live weight sync: ship the current param
        tree through the ring collectives as ONE packed transport.
        Failpoint `rl.weight_sync` fires INSIDE the sync window (a
        crash here models the learner dying mid-broadcast — survivors
        unpark via the trainer's destroy_collective_group)."""
        from ray_tpu import collective, failpoints

        if failpoints.ACTIVE:
            failpoints.fire("rl.weight_sync")
        collective.broadcast_pytree(self.params, src_rank, group_name)
        return self.version

    def init_collective_group(self, world_size: int, rank: int,
                              backend: str = "object_store",
                              group_name: str = "default") -> None:
        from ray_tpu import collective

        collective.init_collective_group(world_size, rank, backend,
                                         group_name)

    def deregister_collective_group(self, group_name: str) -> None:
        """Drop THIS process's state for a stale weight-sync epoch
        (thread pools; the rendezvous actor is destroyed by the
        trainer)."""
        from ray_tpu import collective

        collective.deregister_collective_group(group_name)

    def get_params_numpy(self):
        """Host copy of the param tree.  Transfers are kicked async
        FIRST: a synchronous per-leaf fetch through a tunneled chip
        pays the full RTT per leaf (hundreds of leaves — the same rule
        as broadcast_pytree's packing)."""
        import jax

        for x in jax.tree_util.tree_leaves(self.params):
            try:
                x.copy_to_host_async()
            except AttributeError:
                pass
        return jax.tree.map(np.asarray, self.params)

    def param_hash(self) -> str:
        """Stable content hash of the param tree (determinism tests;
        process-stable — never Python hash())."""
        import hashlib

        import jax

        h = hashlib.blake2b(digest_size=16)
        for leaf in jax.tree_util.tree_leaves(self.params):
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
        return h.hexdigest()

    # ------------------------------------------------------ checkpoints
    def save_async(self, path: str) -> int:
        """Kick an ASYNC checkpoint of (params, opt_state, version) —
        the background writer overlaps the next rollout/update;
        `ckpt_wait()` confirms completion (the trainer only treats a
        checkpoint as the newest resumable state once confirmed)."""
        from ray_tpu.train.checkpoint import Checkpoint

        ckpt = Checkpoint.from_pytree_async(
            {"params": self.params, "opt_state": self.opt_state,
             "version": np.asarray(self.version)}, path=path)
        self._pending_ckpt = (self.version, path, ckpt)
        return self.version

    def ckpt_wait(self) -> tuple | None:
        """Block for the in-flight async save; returns (version, path)
        once durable, None if nothing pending."""
        if self._pending_ckpt is None:
            return None
        version, path, ckpt = self._pending_ckpt
        ckpt.wait()
        self._pending_ckpt = None
        return (version, path)

    def load(self, path: str) -> int:
        """Resume from a COMPLETED checkpoint directory.  The restore
        targets THIS learner's freshly-built state tree: orbax needs
        the target to reconstruct container types (a targetless
        restore hands optax's namedtuple states back as plain dicts —
        the first post-resume update then dies inside the jit)."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.train.checkpoint import Checkpoint

        state = Checkpoint(path).to_pytree(
            target={"params": self.params, "opt_state": self.opt_state,
                    "version": np.asarray(self.version)})
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(jnp.asarray, state["opt_state"])
        self.version = int(np.asarray(state["version"]))
        return self.version

    def pid(self) -> int:
        return os.getpid()


class RLHFTrainer:
    """The online loop driver: rollouts → learner update → async
    checkpoint → live weight broadcast, with rollout-actor and learner
    crash recovery.  `num_rollout_workers=0` runs everything in-process
    (seeded, bit-deterministic — the bench and determinism-test mode);
    otherwise rollout workers (and optionally the learner) are
    ray_tpu actors and weight sync rides the collective broadcast."""

    def __init__(self, config: RLHFConfig | dict | None = None,
                 **overrides):
        cfg = _to_config(config, overrides)
        self.cfg = cfg
        mcfg = _model_config(cfg)
        rng = np.random.default_rng(cfg.seed)
        self.prompts = [rng.integers(
            1, mcfg.vocab_size, cfg.prompt_len).tolist()
            for _ in range(cfg.n_prompts)]
        self._reward = _reward_fn(cfg)
        self._uid = uuid.uuid4().hex[:8]
        self._epoch = 0
        self._group_formed = False
        self._prompt_cursor = 0
        self.version = 0
        self.weight_syncs = 0
        self.weight_sync_ms = 0.0
        self.rollout_regens = 0
        self.learner_restarts = 0
        self._newest_ckpt: tuple | None = None    # (version, path)
        self._worker_version: list[int] = []
        self._local = cfg.num_rollout_workers == 0
        self._build_learner()
        self._build_workers()

    # ------------------------------------------------------------ build
    def _worker_kwargs(self, i: int) -> dict:
        return dict(model=self.cfg.model, params=self.cfg.params,
                    seed=self.cfg.seed, engine=dict(self.cfg.engine),
                    reward_fn=self._reward,
                    name=f"{self.cfg.name}-w{i}")

    def _build_learner(self) -> None:
        lcls = self.cfg.learner_cls or GRPOLearner
        if self.cfg.remote_learner:
            if self._local:
                raise ValueError(
                    "remote_learner requires num_rollout_workers >= 1 "
                    "(a lone in-process loop has nothing to broadcast "
                    "to)")
            cls = ray_tpu.remote(lcls)
            self.learner = cls.options(num_cpus=1).remote(self.cfg)
            # Fail fast if the actor can't build (model typo etc.).
            ray_tpu.get(self.learner.pid.remote())
        else:
            self.learner = lcls(self.cfg)

    def _build_workers(self) -> None:
        from ray_tpu.rl.rollout_llm import LLMRolloutWorker

        wcls = self.cfg.worker_cls or LLMRolloutWorker
        if self._local:
            self.workers = [wcls(**self._worker_kwargs(0))]
            self._worker_version = [0]
            return
        cls = ray_tpu.remote(wcls)
        self.workers = [
            cls.options(num_cpus=1, max_concurrency=4).remote(
                **self._worker_kwargs(i))
            for i in range(self.cfg.num_rollout_workers)]
        ray_tpu.get([w.pid.remote() for w in self.workers])
        self._worker_version = [0] * len(self.workers)

    def _replace_worker(self, i: int) -> None:
        """Respawn a dead rollout actor and bootstrap it to the CURRENT
        policy via a direct object-plane weight push (it initializes at
        version 0 from the seed); the collective group re-forms lazily
        at the next broadcast (membership changed — the elastic-epoch
        rule)."""
        from ray_tpu.rl.rollout_llm import LLMRolloutWorker

        try:
            ray_tpu.kill(self.workers[i])
        except Exception:  # noqa: BLE001 - already dead
            pass
        if self._group_formed:
            # The dead member invalidates the epoch: reap its detached
            # rendezvous NOW (idempotent if _sync_weights already did)
            # — N crashes must not leak N rendezvous actors.
            from ray_tpu import collective

            try:
                collective.destroy_collective_group(
                    self._group_name(),
                    reason=f"rlhf rollout worker {i} replaced; epoch "
                           f"{self._epoch} abandoned")
            except Exception:  # noqa: BLE001 - best effort
                pass
        cls = ray_tpu.remote(self.cfg.worker_cls or LLMRolloutWorker)
        self.workers[i] = cls.options(
            num_cpus=1, max_concurrency=4).remote(
                **self._worker_kwargs(i))
        if self.version > 0:
            # Remote learner: pass the learner call's RESULT REF as the
            # argument — the param tree moves learner→worker over the
            # object plane; the driver never materializes it.
            params = self.learner.get_params_numpy.remote() \
                if self.cfg.remote_learner \
                else self.learner.get_params_numpy()
            v = ray_tpu.get(self.workers[i].update_weights.remote(
                params, self.version), timeout=120)
            self._worker_version[i] = v
        self._group_formed = False

    # ------------------------------------------------- learner recovery
    def _learner_call(self, method: str, *args, timeout: float = 300.0):
        fn = getattr(self.learner, method)
        if self.cfg.remote_learner:
            return ray_tpu.get(fn.remote(*args), timeout=timeout)
        return fn(*args)

    def _recover_learner(self) -> None:
        """A dead learner actor resumes from the newest COMPLETED async
        checkpoint (or from seed-initial state when none finished);
        parked broadcast waiters are drained first so no worker eats a
        collective deadline."""
        self.learner_restarts += 1
        if self._group_formed:
            from ray_tpu import collective

            collective.destroy_collective_group(
                self._group_name(),
                reason=f"rlhf learner died (restart "
                       f"{self.learner_restarts}); weight sync epoch "
                       f"{self._epoch} abandoned")
            self._group_formed = False
        try:
            ray_tpu.kill(self.learner)
        except Exception:  # noqa: BLE001
            pass
        cls = ray_tpu.remote(self.cfg.learner_cls or GRPOLearner)
        self.learner = cls.options(num_cpus=1).remote(self.cfg)
        if self._newest_ckpt is not None:
            self.version = self._learner_call(
                "load", self._newest_ckpt[1])
        else:
            self.version = 0
            ray_tpu.get(self.learner.pid.remote())

    # -------------------------------------------------------- collective
    def _group_name(self) -> str:
        return f"rlhf_w:{self.cfg.name}:{self._uid}:{self._epoch}"

    def _form_group(self) -> None:
        """(Re-)form the weight-broadcast group: learner rank 0, rollout
        workers ranks 1..W — a fresh epoch-suffixed name per membership
        change, the elastic-training rendezvous rule."""
        from ray_tpu import collective

        # Drop every member's LOCAL state for the previous epoch first
        # (op/prefetch thread pools in each process — the rendezvous
        # actor itself is reaped by whoever abandoned the epoch);
        # best-effort, a dead member is being replaced anyway.
        if self._epoch >= 1:
            prev = self._group_name()
            try:
                refs = [w.deregister_collective_group.remote(prev)
                        for w in self.workers]
                if self.cfg.remote_learner:
                    refs.append(
                        self.learner.deregister_collective_group
                        .remote(prev))
                else:
                    self.learner.deregister_collective_group(prev)
                ray_tpu.get(refs, timeout=60)
            except Exception:  # noqa: BLE001 - best effort
                pass
        self._epoch += 1
        name = self._group_name()
        world = 1 + len(self.workers)
        refs = []
        if self.cfg.remote_learner:
            refs.append(self.learner.init_collective_group.remote(
                world, 0, "object_store", name))
        else:
            # In-driver learner: rank 0 lives in THIS process.
            self.learner.init_collective_group(world, 0,
                                               "object_store", name)
        refs += [w.init_collective_group.remote(
            world, r + 1, "object_store", name)
            for r, w in enumerate(self.workers)]
        ray_tpu.get(refs, timeout=120)
        self._group_formed = True

    # ----------------------------------------------------------- rollout
    def _next_prompts(self) -> list[list[int]]:
        n = min(self.cfg.prompts_per_step, len(self.prompts))
        out = [self.prompts[(self._prompt_cursor + j)
                            % len(self.prompts)] for j in range(n)]
        self._prompt_cursor = (self._prompt_cursor + n) \
            % len(self.prompts)
        return out

    def _rollout_kwargs(self) -> dict:
        return dict(group_size=self.cfg.group_size,
                    max_new_tokens=self.cfg.max_new_tokens,
                    temperature=self.cfg.temperature,
                    eos_id=self.cfg.eos_id)

    def _gather_rollouts(self, prompts: list) -> list:
        """Dispatch prompt groups across workers.  In-process mode
        returns trajectory dicts; actor mode returns the rollout REFS
        untouched — they ride to the learner as object-plane refs (the
        learner pulls trajectory bytes straight from each rollout
        worker's arena; the driver never holds the bulk).  Failures
        surface when the learner resolves them — step() heals dead
        workers and regenerates."""
        if self._local:
            return [self.workers[0].rollout(prompts,
                                            **self._rollout_kwargs())]
        shards: dict[int, list] = {}
        for j, p in enumerate(prompts):
            shards.setdefault(j % len(self.workers), []).append(p)
        kw = self._rollout_kwargs()
        return [self.workers[i].rollout.remote(ps, **kw)
                for i, ps in shards.items()]

    def _heal_workers(self) -> None:
        """Replace every dead rollout actor (liveness probe per
        worker); survivors keep their engines — and their prefix
        caches, which is what makes a regenerated group cheap."""
        for i, w in enumerate(self.workers):
            try:
                ray_tpu.get(w.pid.remote(), timeout=60)
            except Exception:  # noqa: BLE001 - dead actor
                self._replace_worker(i)

    # ------------------------------------------------------ weight sync
    def _sync_weights(self) -> None:
        """Push the current learner policy to every generation engine.
        Local mode: a direct update_weights staging.  Actor mode: ring
        broadcast (learner rank 0 + every worker's recv thread), timed
        end-to-end as weight_sync_ms.  Decode never pauses — engines
        swap between sync windows."""
        from ray_tpu import tracing

        t0 = time.perf_counter()
        with tracing.span("rl.weight_sync",
                          attrs={"version": self.version,
                                 "mode": "local" if self._local
                                 else ("driver_learner"
                                       if not self.cfg.remote_learner
                                       else "remote_learner")}):
            self._sync_weights_inner()
        self.weight_syncs += 1
        self.weight_sync_ms += (time.perf_counter() - t0) * 1000.0

    def _sync_weights_inner(self) -> None:
        from ray_tpu import failpoints

        if self._local:
            if failpoints.ACTIVE:
                failpoints.fire("rl.weight_sync")
            v = self.learner.version
            ret = self.workers[0].update_weights(
                self.learner.get_params_numpy(), v)
            if ret == v:
                # Staged (not kill-switched): wait until the engine
                # SWAPPED (stats().weight_version flips) — the next
                # rollout must sample the new policy, or two identical
                # runs could diverge on swap timing (local mode's
                # bit-determinism contract).  A frozen engine
                # (RAY_TPU_RL_WEIGHT_SYNC=0) returned its CURRENT
                # version instead, so there is nothing to wait for.
                deadline = time.monotonic() + 30.0
                while self.workers[0].stats()["weight_version"] < v:
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"weight v{v} never became visible on the "
                            "local engine (loop dead?)")
                    time.sleep(0.001)
            self._worker_version[0] = ret
        elif not self.cfg.remote_learner:
            # Actor workers, in-driver learner: dispatch every
            # receiver FIRST, then broadcast from this process (rank 0
            # blocks until each child consumed its chunks — the
            # receivers above are already running).  A learner crash
            # here IS a driver crash, so no recovery arm.
            if not self._group_formed:
                self._form_group()
            name = self._group_name()
            recv = [w.recv_weights.remote(self.version, name)
                    for w in self.workers]
            self.learner.broadcast_weights(name)
            for i, r in enumerate(recv):
                self._worker_version[i] = ray_tpu.get(r, timeout=300)
        else:
            if not self._group_formed:
                self._form_group()
            name = self._group_name()
            bc = self.learner.broadcast_weights.remote(name)
            recv = [w.recv_weights.remote(self.version, name)
                    for w in self.workers]
            try:
                v = ray_tpu.get(bc, timeout=300)
                for i, r in enumerate(recv):
                    self._worker_version[i] = ray_tpu.get(r,
                                                          timeout=300)
            except Exception:  # noqa: BLE001 - sync failed: diagnose
                # A dead ROLLOUT worker or a collective deadline also
                # lands here — probe the learner before condemning it
                # (recovering a HEALTHY learner would roll training
                # back to the last checkpoint, or to seed with
                # checkpoint_every=0).
                learner_dead = False
                try:
                    self._learner_call("pid", timeout=60)
                except Exception:  # noqa: BLE001
                    learner_dead = True
                if learner_dead:
                    self._recover_learner()
                else:
                    from ray_tpu import collective

                    # Unpark any receiver still waiting on the stale
                    # epoch and reap its detached rendezvous — then
                    # replace whichever worker actually died.
                    collective.destroy_collective_group(
                        self._group_name(),
                        reason="rlhf weight sync failed (rollout "
                               "worker died mid-broadcast?); epoch "
                               f"{self._epoch} abandoned")
                    self._group_formed = False
                    self._heal_workers()
                # Drain any still-parked receivers, then re-sync on a
                # fresh epoch so every (possibly replaced) member lands
                # on the current policy.
                for r in recv:
                    try:
                        ray_tpu.get(r, timeout=60)
                    except Exception:  # noqa: BLE001 - drained/aborted
                        pass
                self._form_group()
                name = self._group_name()
                bc = self.learner.broadcast_weights.remote(name)
                recv = [w.recv_weights.remote(self.version, name)
                        for w in self.workers]
                ray_tpu.get(bc, timeout=300)
                for i, r in enumerate(recv):
                    self._worker_version[i] = ray_tpu.get(r,
                                                          timeout=300)

    def _lag_exceeded(self) -> bool:
        return (self.version - min(self._worker_version)
                > self.cfg.max_weight_lag)

    def _update_with_recovery(self, trajs):
        """Learner update with crash recovery.  A failure here is
        either the learner dying (liveness probe fails → rebuild from
        the newest async checkpoint, retry) or a trajectory ref whose
        rollout worker died (probe passes → re-raise so step()
        regenerates the group)."""
        try:
            return self._learner_call("update", trajs)
        except Exception:  # noqa: BLE001
            if not self.cfg.remote_learner:
                raise
            try:
                self._learner_call("pid", timeout=60)
                alive = True
            except Exception:  # noqa: BLE001
                alive = False
            if alive:
                raise        # lost trajectories — step() regenerates
            self._recover_learner()
            return self._learner_call("update", trajs)

    # ------------------------------------------------------------- loop
    def step(self) -> dict:
        """One full cycle: rollout → update → (async checkpoint) →
        (broadcast).  The staleness bound runs FIRST: generation must
        never start more than max_weight_lag versions behind."""
        if self.cfg.sync_every and self.version and self._lag_exceeded():
            # sync_every=0 means NEVER broadcast — the lag bound only
            # applies when sync is enabled at all.
            self._sync_weights()
        prompts = self._next_prompts()
        if self._local:
            metrics = self._learner_call(
                "update", self._gather_rollouts(prompts))
        else:
            metrics = last_err = None
            for _attempt in range(1 + self.cfg.rollout_retries):
                trajs = self._gather_rollouts(prompts)
                try:
                    metrics = self._update_with_recovery(trajs)
                    last_err = None
                    break
                except Exception as e:  # noqa: BLE001 - rollout lost
                    last_err = e
                    self.rollout_regens += 1
                    self._heal_workers()
            if metrics is None:
                raise RuntimeError(
                    f"rollouts failed {1 + self.cfg.rollout_retries}x "
                    "(workers crash-looping?)") from last_err
        self.version = metrics["version"]
        if self.cfg.checkpoint_every and \
                self.version % self.cfg.checkpoint_every == 0:
            self._checkpoint()
        if self.cfg.sync_every and \
                self.version % self.cfg.sync_every == 0:
            self._sync_weights()
        metrics["weight_syncs"] = self.weight_syncs
        metrics["rollout_regens"] = self.rollout_regens
        metrics["learner_restarts"] = self.learner_restarts
        return metrics

    def run(self, n_updates: int) -> list[dict]:
        return [self.step() for _ in range(n_updates)]

    def _checkpoint(self) -> None:
        """Async save; the PREVIOUS save is confirmed (waited) first and
        becomes the newest resumable checkpoint — so the learner-crash
        recovery never points at a half-written directory."""
        base = self.cfg.checkpoint_dir
        if base is None:
            import tempfile

            base = tempfile.mkdtemp(prefix="rlhf-ckpt-")
            self.cfg.checkpoint_dir = base
        done = self._learner_call("ckpt_wait")
        if done is not None:
            self._newest_ckpt = done
        path = os.path.join(base, f"v{self.version:06d}")
        self._learner_call("save_async", path)

    def flush_checkpoints(self) -> tuple | None:
        """Force the in-flight save durable (tests/benches call this
        before killing the learner so there IS a newest checkpoint)."""
        done = self._learner_call("ckpt_wait")
        if done is not None:
            self._newest_ckpt = done
        return self._newest_ckpt

    # ------------------------------------------------------------ admin
    def stats(self) -> dict:
        out = {
            "version": self.version,
            "weight_syncs": self.weight_syncs,
            "weight_sync_ms": round(self.weight_sync_ms, 3),
            "rollout_regens": self.rollout_regens,
            "learner_restarts": self.learner_restarts,
            "epoch": self._epoch,
            "worker_versions": list(self._worker_version),
            "newest_ckpt": self._newest_ckpt,
        }
        if self._local:
            out["workers"] = [self.workers[0].stats()]
        return out

    def shutdown(self) -> None:
        if self._local:
            self.workers[0].stop()
            return
        if self._group_formed:
            from ray_tpu import collective

            try:
                collective.destroy_collective_group(
                    self._group_name(), reason="rlhf trainer shutdown")
            except Exception:  # noqa: BLE001
                pass
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        if self.cfg.remote_learner:
            try:
                ray_tpu.kill(self.learner)
            except Exception:  # noqa: BLE001
                pass
