"""DreamerV3: model-based RL — learn a world model, act in imagination.

Analog of ray: rllib/algorithms/dreamerv3/ (dreamerv3.py, torch RSSM in
dreamerv3_torch_model.py) — compacted to the discrete-action core and
re-shaped for XLA: the RSSM rollout, the imagination rollout, and both
optimizer steps are single jitted programs built on `lax.scan` (no
Python step loops under jit; static [B,T]/[H] shapes).

Kept from the paper: categorical latents (groups × classes) with
straight-through gradients, KL balancing with free bits (dyn 0.5 /
rep 0.1, 1 nat), reward/continue heads, imagination-trained actor-critic
with λ-returns and entropy regularization.  Simplified vs the reference
(documented, CartPole-scale): plain MSE decoder/reward (no
symlog/twohot), no critic-EMA regularizer, shared Adam per module
group.  rllib: dreamerv3.py:292 training_step drives the same
world-model → imagine → actor/critic cadence.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import make_env


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.actor_lr = 1e-4
        self.critic_lr = 1e-4
        self.deter = 64                 # GRU state
        self.groups = 4                 # latent groups
        self.classes = 4                # classes per group
        self.hidden = 64
        self.batch_length = 16          # T per training sequence
        self.batch_rows = 8             # B sequences per update
        self.horizon = 10               # imagination steps
        self.gamma = 0.997
        self.gae_lambda = 0.95
        self.entropy_coeff = 3e-3
        self.free_bits = 1.0
        self.replay_capacity = 20000
        self.updates_per_step = 4
        self.train_batch_size = 256     # env steps collected per step()

    def training(self, *, horizon=None, batch_length=None,
                 updates_per_step=None, entropy_coeff=None, **kw):
        for name, v in [("horizon", horizon),
                        ("batch_length", batch_length),
                        ("updates_per_step", updates_per_step),
                        ("entropy_coeff", entropy_coeff)]:
            if v is not None:
                setattr(self, name, v)
        super().training(**kw)
        return self


def _mlp(rng, sizes):
    from ray_tpu.rl.models import mlp_init

    return mlp_init(rng, sizes)


def dreamer_params_init(rng, obs_dim: int, n_actions: int, cfg: dict):
    import jax

    deter = cfg["deter"]
    stoch = cfg["groups"] * cfg["classes"]
    hid = cfg["hidden"]
    embed = hid
    ks = jax.random.split(rng, 9)
    import jax.numpy as jnp

    return {
        "enc": _mlp(ks[0], [obs_dim, hid, embed]),
        # GRU: input [z + one-hot action] with state h → candidate/gates.
        "gru_w": jax.random.normal(
            ks[1], (stoch + n_actions + deter, 3 * deter),
            jnp.float32) * 0.02,
        "gru_b": jnp.zeros((3 * deter,), jnp.float32),
        "prior": _mlp(ks[2], [deter, hid, stoch]),
        "post": _mlp(ks[3], [deter + embed, hid, stoch]),
        "dec": _mlp(ks[4], [deter + stoch, hid, obs_dim]),
        "rew": _mlp(ks[5], [deter + stoch, hid, 1]),
        "cont": _mlp(ks[6], [deter + stoch, hid, 1]),
        "actor": _mlp(ks[7], [deter + stoch, hid, n_actions]),
        "critic": _mlp(ks[8], [deter + stoch, hid, 1]),
    }


class DreamerV3(Algorithm):
    """Compact DreamerV3 (see module docstring for scope)."""

    def setup(self, config: dict) -> None:
        import jax
        import optax

        defaults = type(self).get_default_config().to_dict()
        defaults.update(config or {})
        self.cfg = defaults
        probe = make_env(self.cfg["env"], seed=0)
        self.obs_dim = probe.obs_dim
        self.n_actions = probe.n_actions
        # Collection runs in-process (the recurrent policy isn't a flat
        # param dict shippable to EnvRunner actors; rllib's DreamerV3
        # drives its own EnvRunner subclass the same way).
        from ray_tpu.rl.replay import ReplayBuffer

        self.replay = ReplayBuffer(self.cfg["replay_capacity"],
                                   seed=self.cfg["seed"])
        rng = jax.random.PRNGKey(self.cfg["seed"])
        self.params = dreamer_params_init(rng, self.obs_dim,
                                          self.n_actions, self.cfg)
        self._rng = jax.random.PRNGKey(self.cfg["seed"] + 1)
        wm_keys = ("enc", "gru_w", "gru_b", "prior", "post", "dec",
                   "rew", "cont")
        self._wm_keys = wm_keys
        self.tx_wm = optax.adam(self.cfg["lr"])
        self.tx_actor = optax.adam(self.cfg["actor_lr"])
        self.tx_critic = optax.adam(self.cfg["critic_lr"])
        self.opt_wm = self.tx_wm.init({k: self.params[k] for k in wm_keys})
        self.opt_actor = self.tx_actor.init(self.params["actor"])
        self.opt_critic = self.tx_critic.init(self.params["critic"])
        self._update = self._build_update()
        self._params_np = None           # env runners use _policy below
        self._timesteps = 0
        self._episode_returns: list[float] = []

    # ------------------------------------------------------------ jit core
    def _build_update(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl.models import mlp_apply

        cfg = self.cfg
        G, C = cfg["groups"], cfg["classes"]
        deter = cfg["deter"]
        stoch = G * C
        n_act = self.n_actions
        gamma, lam = cfg["gamma"], cfg["gae_lambda"]
        H = cfg["horizon"]
        ent_coeff = cfg["entropy_coeff"]
        free = cfg["free_bits"]
        wm_keys = self._wm_keys

        def gru(p, h, x):
            # Light GRU (fused [x,h] projection; candidate gated by r
            # multiplicatively — one matmul per step keeps the scan MXU-
            # friendly).
            gates = jnp.concatenate([x, h], -1) @ p["gru_w"] + p["gru_b"]
            r, u, c = jnp.split(gates, 3, axis=-1)
            r, u = jax.nn.sigmoid(r), jax.nn.sigmoid(u)
            cand = jnp.tanh(r * c)
            return u * h + (1 - u) * cand

        def latent_dist(logits):
            lg = logits.reshape(logits.shape[:-1] + (G, C))
            return jax.nn.log_softmax(lg, axis=-1)

        def sample_latent(rng, logits):
            """Straight-through categorical sample per group → flat."""
            logp = latent_dist(logits)
            g = jax.random.gumbel(rng, logp.shape)
            idx = jnp.argmax(logp + g, axis=-1)
            onehot = jax.nn.one_hot(idx, C)
            probs = jnp.exp(logp)
            st = onehot + probs - jax.lax.stop_gradient(probs)
            return st.reshape(st.shape[:-2] + (stoch,))

        def kl(lp_a, lp_b):
            """KL over the grouped categoricals, summed across groups."""
            return jnp.sum(jnp.exp(lp_a) * (lp_a - lp_b), axis=(-2, -1))

        def wm_loss(wm, batch, rng):
            """Posterior rollout over [B,T]; recon+reward+cont+KL."""
            obs = batch["obs"]                         # [B,T,obs]
            B, T = obs.shape[:2]
            act = jax.nn.one_hot(batch["actions"], n_act)
            embed = mlp_apply(wm["enc"], obs, jnp)     # [B,T,embed]
            resets = jnp.maximum(batch["dones"], batch["truncs"])

            def step(carry, xs):
                h, z, rng_c = carry
                emb_t, act_prev, reset_prev = xs
                # Episode edges cut the recurrence inside a sequence.
                keep = (1.0 - reset_prev)[:, None]
                h = h * keep
                z = z * keep
                h = gru(wm, h, jnp.concatenate([z, act_prev], -1))
                prior_logits = mlp_apply(wm["prior"], h, jnp)
                post_logits = mlp_apply(
                    wm["post"], jnp.concatenate([h, emb_t], -1), jnp)
                rng_c, k = jax.random.split(rng_c)
                z = sample_latent(k, post_logits)
                return (h, z, rng_c), (h, z, prior_logits, post_logits)

            h0 = jnp.zeros((B, deter))
            z0 = jnp.zeros((B, stoch))
            act_prev = jnp.concatenate(
                [jnp.zeros_like(act[:, :1]), act[:, :-1]], 1)
            reset_prev = jnp.concatenate(
                [jnp.zeros_like(resets[:, :1]), resets[:, :-1]], 1)
            (_, _, _), (hs, zs, priors, posts) = jax.lax.scan(
                step, (h0, z0, rng),
                (embed.transpose(1, 0, 2), act_prev.transpose(1, 0, 2),
                 reset_prev.T))
            hs = hs.transpose(1, 0, 2)                 # [B,T,deter]
            zs = zs.transpose(1, 0, 2)
            priors = priors.transpose(1, 0, 2)
            posts = posts.transpose(1, 0, 2)
            feat = jnp.concatenate([hs, zs], -1)
            recon = mlp_apply(wm["dec"], feat, jnp)
            rew = mlp_apply(wm["rew"], feat, jnp)[..., 0]
            cont = mlp_apply(wm["cont"], feat, jnp)[..., 0]
            lp_prior, lp_post = latent_dist(priors), latent_dist(posts)
            dyn = jnp.maximum(
                kl(jax.lax.stop_gradient(lp_post), lp_prior), free)
            rep = jnp.maximum(
                kl(lp_post, jax.lax.stop_gradient(lp_prior)), free)
            recon_loss = jnp.mean(jnp.sum((recon - obs) ** 2, -1))
            rew_loss = jnp.mean((rew - batch["rewards"]) ** 2)
            cont_target = 1.0 - batch["dones"]
            cont_loss = jnp.mean(
                optax_sigmoid_ce(cont, cont_target))
            kl_loss = jnp.mean(0.5 * dyn + 0.1 * rep)
            total = recon_loss + rew_loss + cont_loss + kl_loss
            aux = {"recon": recon_loss, "reward_loss": rew_loss,
                   "cont_loss": cont_loss, "kl": kl_loss,
                   "feat": feat}
            return total, aux

        def optax_sigmoid_ce(logits, labels):
            return jnp.maximum(logits, 0) - logits * labels + \
                jnp.log1p(jnp.exp(-jnp.abs(logits)))

        def imagine(wm, actor, feat0, rng):
            """Roll the PRIOR forward H steps under the actor."""
            B = feat0.shape[0]
            h0 = feat0[:, :deter]
            z0 = feat0[:, deter:]

            def step(carry, _):
                h, z, rng_c = carry
                logits = mlp_apply(actor, jnp.concatenate([h, z], -1),
                                   jnp)
                rng_c, k1, k2 = jax.random.split(rng_c, 3)
                a_idx = jax.random.categorical(k1, logits)
                a = jax.nn.one_hot(a_idx, n_act)
                logp_a = jnp.take_along_axis(
                    jax.nn.log_softmax(logits, -1), a_idx[:, None],
                    -1)[:, 0]
                ent = -jnp.sum(jax.nn.softmax(logits, -1) *
                               jax.nn.log_softmax(logits, -1), -1)
                h = gru(wm, h, jnp.concatenate([z, a], -1))
                z = sample_latent(k2, mlp_apply(wm["prior"], h, jnp))
                return (h, z, rng_c), (h, z, logp_a, ent)

            (_, _, _), (hs, zs, logps, ents) = jax.lax.scan(
                step, (h0, z0, rng), None, length=H)
            feat = jnp.concatenate([hs, zs], -1)       # [H,B,feat]
            return feat, logps, ents

        def ac_loss(actor_critic, wm, feat0, rng):
            actor, critic = actor_critic
            feat, logps, ents = imagine(
                jax.lax.stop_gradient(wm), actor, feat0, rng)
            feat_sg = jax.lax.stop_gradient(feat)
            rew = mlp_apply(wm["rew"], feat_sg, jnp)[..., 0]   # [H,B]
            cont = jax.nn.sigmoid(
                mlp_apply(wm["cont"], feat_sg, jnp)[..., 0])
            v = mlp_apply(critic, feat_sg, jnp)[..., 0]        # [H,B]
            disc = gamma * cont

            def bwd(acc, xs):
                r_t, d_t, v_next = xs
                ret = r_t + d_t * ((1 - lam) * v_next + lam * acc)
                return ret, ret

            v_last = v[-1]
            _, rets = jax.lax.scan(
                bwd, v_last,
                (rew[:-1][::-1], disc[:-1][::-1], v[1:][::-1]))
            rets = rets[::-1]                                  # [H-1,B]
            adv = jax.lax.stop_gradient(rets - v[:-1])
            adv_n = (adv - adv.mean()) / (adv.std() + 1e-8)
            actor_loss = -jnp.mean(logps[:-1] * adv_n) \
                - ent_coeff * jnp.mean(ents)
            critic_loss = jnp.mean(
                (v[:-1] - jax.lax.stop_gradient(rets)) ** 2)
            total = actor_loss + critic_loss
            return total, {"actor_loss": actor_loss,
                           "critic_loss": critic_loss,
                           "entropy": jnp.mean(ents),
                           "value_mean": jnp.mean(v)}

        tx_wm, tx_actor, tx_critic = (self.tx_wm, self.tx_actor,
                                      self.tx_critic)

        def update(params, opts, batch, rng):
            opt_wm, opt_actor, opt_critic = opts
            wm = {k: params[k] for k in wm_keys}
            rng, k1, k2 = jax.random.split(rng, 3)
            (wl, aux), gw = jax.value_and_grad(
                wm_loss, has_aux=True)(wm, batch, k1)
            upd, opt_wm = tx_wm.update(gw, opt_wm, wm)
            import optax as _optax

            wm = _optax.apply_updates(wm, upd)
            params = {**params, **wm}
            feat0 = jax.lax.stop_gradient(
                aux.pop("feat").reshape(-1, deter + stoch))
            (al, aaux), (ga, gc) = jax.value_and_grad(
                ac_loss, has_aux=True)(
                    (params["actor"], params["critic"]), wm, feat0, k2)
            upd_a, opt_actor = tx_actor.update(ga, opt_actor,
                                               params["actor"])
            upd_c, opt_critic = tx_critic.update(gc, opt_critic,
                                                 params["critic"])
            params = {**params,
                      "actor": _optax.apply_updates(params["actor"],
                                                    upd_a),
                      "critic": _optax.apply_updates(params["critic"],
                                                     upd_c)}
            metrics = {"wm_loss": wl, "ac_loss": al, **aux, **aaux}
            return params, (opt_wm, opt_actor, opt_critic), metrics

        return jax.jit(update)

    # -------------------------------------------------------- acting glue
    def _policy_logits_fn(self):
        """Feedforward acting slice of the recurrent model: actor over
        [h=0, z=mode(post(h=0, enc(obs)))].  CartPole-scale envs are
        fully observed, so the posterior features carry the state — this
        keeps collection simple while exercising the exact heads the
        imagination trains."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl.models import mlp_apply

        p = self.params

        def logits_fn(obs_np):
            obs = jnp.asarray(obs_np, jnp.float32)
            single = obs.ndim == 1
            if single:
                obs = obs[None]
            emb = mlp_apply(p["enc"], obs, jnp)
            h = jnp.zeros((obs.shape[0], self.cfg["deter"]))
            post = mlp_apply(p["post"],
                             jnp.concatenate([h, emb], -1), jnp)
            G, C = self.cfg["groups"], self.cfg["classes"]
            lg = post.reshape(post.shape[:-1] + (G, C))
            mode = jax.nn.one_hot(jnp.argmax(lg, -1), C)
            z = mode.reshape(mode.shape[:-2] + (G * C,))
            out = mlp_apply(p["actor"], jnp.concatenate([h, z], -1),
                            jnp)
            return np.asarray(out[0] if single else out)

        return logits_fn

    def training_step(self) -> dict:
        import jax
        import jax.numpy as jnp

        per = max(1, self.cfg["train_batch_size"]
                  // self.cfg["num_env_runners"])
        logits_fn = self._policy_logits_fn()
        fragments = self._sample_with(logits_fn, per)
        for b in fragments:
            self._episode_returns.extend(b.pop("episode_returns").tolist())
            self._timesteps += len(b["obs"])
            self.replay.add_batch(b)
        if len(self.replay) < self.cfg["batch_rows"] * \
                self.cfg["batch_length"]:
            return {"buffer": float(len(self.replay))}
        metrics = {}
        for _ in range(self.cfg["updates_per_step"]):
            batch = self._sample_sequences()
            self._rng, k = jax.random.split(self._rng)
            self.params, opts, m = self._update(
                self.params,
                (self.opt_wm, self.opt_actor, self.opt_critic),
                {k2: jnp.asarray(v) for k2, v in batch.items()}, k)
            self.opt_wm, self.opt_actor, self.opt_critic = opts
            metrics = {k2: float(v) for k2, v in m.items()}
        return metrics

    def _sample_with(self, logits_fn, per: int) -> list[dict]:
        """Local (driver-side) sampling with the composed policy: the
        recurrent model's policy isn't a flat param dict, so collection
        runs the envs in-process (CartPole-scale; rllib's DreamerV3 also
        drives its own EnvRunner subclass)."""
        if not hasattr(self, "_local_envs"):
            self._local_envs = [
                make_env(self.cfg["env"], seed=1000 + 7919 * i)
                for i in range(self.cfg["num_env_runners"])]
            self._local_obs = [e.reset() for e in self._local_envs]
            self._local_rng = np.random.default_rng(self.cfg["seed"] + 5)
            self._local_ret = [0.0] * len(self._local_envs)
        out = []
        for ei, env in enumerate(self._local_envs):
            cols = {k: [] for k in ("obs", "actions", "rewards", "dones",
                                    "truncs")}
            rets = []
            obs = self._local_obs[ei]
            for _ in range(per):
                logits = logits_fn(obs)
                z = logits - logits.max()
                prob = np.exp(z) / np.exp(z).sum()
                a = int(self._local_rng.choice(len(prob), p=prob))
                nxt, r, term, trunc = env.step(a)
                cols["obs"].append(np.asarray(obs, np.float32))
                cols["actions"].append(a)
                cols["rewards"].append(r)
                cols["dones"].append(float(term))
                cols["truncs"].append(float(trunc and not term))
                self._local_ret[ei] += r
                if term or trunc:
                    rets.append(self._local_ret[ei])
                    self._local_ret[ei] = 0.0
                    obs = env.reset()
                else:
                    obs = nxt
            self._local_obs[ei] = obs
            out.append({
                "obs": np.stack(cols["obs"]),
                "actions": np.asarray(cols["actions"], np.int64),
                "rewards": np.asarray(cols["rewards"], np.float32),
                "dones": np.asarray(cols["dones"], np.float32),
                "truncs": np.asarray(cols["truncs"], np.float32),
                "episode_returns": np.asarray(rets, np.float32),
            })
        return out

    def _sample_sequences(self) -> dict:
        """[B,T] contiguous windows from the replay's flat storage."""
        B, T = self.cfg["batch_rows"], self.cfg["batch_length"]
        data = self.replay.storage()
        n = len(data["obs"])
        rng = np.random.default_rng(int(self._timesteps) + 13)
        starts = rng.integers(0, max(1, n - T), size=B)
        return {k: np.stack([v[s:s + T] for s in starts])
                for k, v in data.items()
                if k in ("obs", "actions", "rewards", "dones", "truncs")}

    def cleanup(self) -> None:
        pass


DreamerV3._default_config = DreamerV3Config()
DreamerV3Config.algo_class = DreamerV3
