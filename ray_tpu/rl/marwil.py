"""MARWIL: monotonic advantage re-weighted imitation learning.

Analog of ray: rllib/algorithms/marwil/marwil.py (MARWIL / MARWILConfig,
torch loss in marwil_torch_learner.py) — offline policy learning that
upgrades BC with exponential advantage weighting: actions that
outperformed the logged value estimate get up-weighted
(w = exp(beta * A / c)), beta=0 reduces exactly to BC.  The value head
trains on monte-carlo returns from the logged episodes.

Offline batches need (obs, actions) plus either "returns" or
(rewards, dones) to derive discounted returns-to-go.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rl.bc import BC, BCConfig


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0             # 0 => plain BC
        self.vf_coeff = 1.0
        self.w_clip = 20.0          # cap on the exp advantage weight

    def training(self, *, beta=None, vf_coeff=None, w_clip=None,
                 **kw) -> "MARWILConfig":
        for name, v in [("beta", beta), ("vf_coeff", vf_coeff),
                        ("w_clip", w_clip)]:
            if v is not None:
                setattr(self, name, v)
        super().training(**kw)
        return self


def discounted_returns(rewards: np.ndarray, dones: np.ndarray,
                       gamma: float) -> np.ndarray:
    """Per-step discounted returns-to-go, resetting at episode ends."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        acc = rewards[t] + gamma * (1.0 - dones[t]) * acc
        out[t] = acc
    return out


class MARWIL(BC):
    _offline_keys = ("obs", "actions", "returns")

    @staticmethod
    def loss_builder(config: dict):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl import models

        beta = config.get("beta", 1.0)
        vf_coeff = config.get("vf_coeff", 1.0)
        w_clip = config.get("w_clip", 20.0)

        def loss_fn(params, batch):
            logits = models.policy_logits(params, batch["obs"], jnp)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(
                logp, batch["actions"][:, None], axis=-1)[:, 0]
            v = models.value(params, batch["obs"], jnp)
            adv = batch["returns"] - v
            vf_loss = jnp.mean(adv ** 2)
            # Weight by exp(beta * normalized advantage); stop-grad so
            # the policy term never trains the critic through the weight
            # (marwil_torch_learner.py).
            adv_sg = jax.lax.stop_gradient(adv)
            norm = jnp.sqrt(jnp.mean(adv_sg ** 2) + 1e-8)
            w = jnp.minimum(jnp.exp(beta * adv_sg / norm), w_clip)
            pi_loss = jnp.mean(w * nll)
            total = pi_loss + vf_coeff * vf_loss
            acc = jnp.mean(
                (jnp.argmax(logits, axis=-1) == batch["actions"])
                .astype(jnp.float32))
            return total, {"marwil_loss": pi_loss, "vf_loss": vf_loss,
                           "mean_weight": jnp.mean(w),
                           "action_accuracy": acc}
        return loss_fn

    def setup(self, config: dict) -> None:
        config = dict(config or {})
        offline = config.get("offline_data")
        if offline is not None and not hasattr(offline, "to_numpy") \
                and "returns" not in offline:
            # Derive returns-to-go from logged rewards/dones.
            gamma = config.get("gamma", 0.99)
            offline = dict(offline)
            offline["returns"] = discounted_returns(
                np.asarray(offline["rewards"], np.float32),
                np.asarray(offline["dones"], np.float32), gamma)
            config["offline_data"] = offline
        super().setup(config)


MARWIL._default_config = MARWILConfig()
MARWILConfig.algo_class = MARWIL
