"""SAC (discrete-action): maximum-entropy off-policy actor-critic.

Analog of ray: rllib/algorithms/sac/ (SAC / SACConfig; torch losses in
sac_torch_learner.py).  Discrete variant: categorical policy + twin Q
networks + learned temperature, with the expectation over actions taken
exactly (sum over the categorical support) instead of the reparameterized
sample the continuous variant needs.

TPU-native shape: actor/critic/temperature losses combine into ONE jitted
update (stop-gradients route each term to its own sub-tree), and the
polyak target sync is a jitted post-update transform — one XLA program per
minibatch, no per-network Python dispatch.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.replay import ReplayBuffer


def sac_params_init(rng, obs_dim: int, n_actions: int, hidden: int = 64):
    """Policy + twin Q + frozen twin targets + log-temperature."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl import models

    k1, k2, k3 = jax.random.split(rng, 3)
    q1 = models.mlp_init(k2, [obs_dim, hidden, hidden, n_actions])
    q2 = models.mlp_init(k3, [obs_dim, hidden, hidden, n_actions])
    return {
        "pi": models.mlp_init(k1, [obs_dim, hidden, hidden, n_actions]),
        "q1": q1, "q2": q2,
        # Targets start as copies; they receive zero gradient (stop_grad in
        # the loss) and move only via the polyak post-update.
        "q1_t": jax.tree.map(jnp.array, q1),
        "q2_t": jax.tree.map(jnp.array, q2),
        "log_alpha": jnp.zeros(()),
    }


def sac_post_update(config: dict):
    """Polyak averaging of the target critics (ray: SAC tau)."""
    import jax

    tau = config.get("tau", 0.005)

    def post(params):
        for live, tgt in (("q1", "q1_t"), ("q2", "q2_t")):
            params[tgt] = jax.tree.map(
                lambda t, l: (1.0 - tau) * t + tau * l,
                params[tgt], params[live])
        return params
    return post


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.tau = 0.005
        self.replay_capacity = 50_000
        self.learning_starts = 500
        self.train_batch_size = 256
        self.sgd_batch_size = 64
        self.target_entropy = None   # default: 0.98 * log(n_actions)
        self.updates_per_step = 4

    def training(self, *, tau=None, replay_capacity=None,
                 learning_starts=None, sgd_batch_size=None,
                 target_entropy=None, updates_per_step=None,
                 **kw) -> "SACConfig":
        for name, v in [("tau", tau), ("replay_capacity", replay_capacity),
                        ("learning_starts", learning_starts),
                        ("sgd_batch_size", sgd_batch_size),
                        ("target_entropy", target_entropy),
                        ("updates_per_step", updates_per_step)]:
            if v is not None:
                setattr(self, name, v)
        super().training(**kw)
        return self


class SAC(Algorithm):
    @staticmethod
    def loss_builder(config: dict):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl import models

        gamma = config.get("gamma", 0.99)
        n_actions = config["n_actions"]
        target_ent = config.get("target_entropy")
        if target_ent is None:
            target_ent = 0.98 * float(np.log(n_actions))
        sg = jax.lax.stop_gradient

        def loss_fn(params, batch):
            alpha = jnp.exp(params["log_alpha"])

            # --- critic loss (targets use frozen nets + current policy) --
            logp_next = jax.nn.log_softmax(
                models.mlp_apply(params["pi"], batch["next_obs"], jnp))
            p_next = jnp.exp(logp_next)
            q1_t = models.mlp_apply(params["q1_t"], batch["next_obs"], jnp)
            q2_t = models.mlp_apply(params["q2_t"], batch["next_obs"], jnp)
            v_next = jnp.sum(
                p_next * (jnp.minimum(q1_t, q2_t) - alpha * logp_next),
                axis=-1)
            target = sg(batch["rewards"] +
                        gamma * (1.0 - batch["dones"]) * v_next)
            a = batch["actions"][:, None]
            q1 = jnp.take_along_axis(
                models.mlp_apply(params["q1"], batch["obs"], jnp), a,
                axis=-1)[:, 0]
            q2 = jnp.take_along_axis(
                models.mlp_apply(params["q2"], batch["obs"], jnp), a,
                axis=-1)[:, 0]
            critic_loss = 0.5 * (jnp.mean((q1 - target) ** 2) +
                                 jnp.mean((q2 - target) ** 2))

            # --- actor loss (critics frozen) ----------------------------
            logp_pi = jax.nn.log_softmax(
                models.mlp_apply(params["pi"], batch["obs"], jnp))
            p_pi = jnp.exp(logp_pi)
            q_min = sg(jnp.minimum(
                models.mlp_apply(params["q1"], batch["obs"], jnp),
                models.mlp_apply(params["q2"], batch["obs"], jnp)))
            actor_loss = jnp.mean(jnp.sum(
                p_pi * (sg(alpha) * logp_pi - q_min), axis=-1))

            # --- temperature loss (policy frozen) -----------------------
            entropy = -jnp.sum(sg(p_pi * logp_pi), axis=-1)
            alpha_loss = jnp.mean(
                params["log_alpha"] * sg(entropy - target_ent))

            total = critic_loss + actor_loss + alpha_loss
            return total, {"critic_loss": critic_loss,
                           "actor_loss": actor_loss,
                           "alpha": alpha,
                           "entropy": jnp.mean(entropy)}
        return loss_fn

    def setup(self, config: dict) -> None:
        config = dict(config or {})
        config.setdefault("params_builder", sac_params_init)
        config.setdefault("post_update_builder", sac_post_update)
        super().setup(config)
        self.replay = ReplayBuffer(self.cfg["replay_capacity"],
                                   seed=self.cfg["seed"])

    def training_step(self) -> dict:
        # Collection rides the shared env→learner connector pipeline
        # (RecordEpisodeMetrics + ConcatFragments), like PPO/DQN/IMPALA.
        batch = self._collect(with_gae=False)
        self.replay.add_batch(batch)
        if len(self.replay) < self.cfg["learning_starts"]:
            return {"buffer_size": float(len(self.replay))}
        metrics: dict = {}
        for _ in range(self.cfg.get("updates_per_step", 4)):
            sample = self.replay.sample(self.cfg["sgd_batch_size"])
            metrics = self.learner_group.update(sample, num_sgd_iter=1)
        self._params_np = self.learner_group.get_params_numpy()
        return metrics


SAC._default_config = SACConfig()
SACConfig.algo_class = SAC
