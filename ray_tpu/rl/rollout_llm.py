"""LLM rollout workers: GRPO-group generation through the serve engine.

The online-RLHF sampling half (ROADMAP item 5, "serve-engine rollouts
feeding a TPU learner"): a rollout worker owns a paged-KV `LLMEngine`
(ray_tpu.serve.llm) and generates K completions per prompt — a GRPO
*group*.  Every member of a group shares its prompt, so after the first
member prefills it the radix prefix cache serves the other K-1 prompts
from cached blocks: group rollouts cost ~one prompt prefill plus K
decode streams (the bench asserts the hit rate).

Per-trajectory behavior logprobs come from the model's teacher-forced
scoring path (`llama.token_logprobs`) under the params that generated
them — the engine samples from exactly these logits, so the scored
logprob IS the behavior policy's.  Live weight sync
(`LLMEngine.update_weights`) can swap params between a completion's
decode windows; scoring then uses the newest resident tree, which is
the bounded off-policy staleness GRPO's clipped ratio absorbs (the
trainer's `max_weight_lag` bounds it).

Trajectories return as plain numpy dicts: called through an actor
handle, the result rides the object plane as a ref the trainer hands
straight to the learner.  Workers participate in the learner's weight
broadcast over the ring collectives (`recv_weights`) on a separate
actor thread, so generation never pauses for a policy update.

Failpoint site: `rl.rollout_step` (fires at rollout entry — a `crash`
arm models a rollout actor dying with a group in flight; the trainer
regenerates the group on a replacement, where the prefix cache makes
the retry cheap).

Layering: built only on core primitives and public library facades
(serve engine, collective, failpoints) — enforced by test_layering.py.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable

import numpy as np


def _pow2(n: int, lo: int = 8) -> int:
    m = lo
    while m < n:
        m *= 2
    return m


# ------------------------------------------------------------- rewards
def near_token_reward(target: int, vocab: int) -> Callable:
    """Dense builtin reward: mean over completion tokens of
    1 - |tok - target| / vocab.  Dense (every completion scores
    differently) so small GRPO groups see non-degenerate within-group
    variance — the learning-test reward."""
    def fn(prompt, completion) -> float:
        c = np.asarray(completion, np.float32)
        if c.size == 0:
            return 0.0
        return float(np.mean(1.0 - np.abs(c - float(target)) / vocab))
    return fn


def target_token_reward(target: int) -> Callable:
    """Sparse builtin reward: fraction of completion tokens equal to
    the target id."""
    def fn(prompt, completion) -> float:
        c = np.asarray(completion)
        return float(np.mean(c == target)) if c.size else 0.0
    return fn


# ------------------------------------------------------------- metrics
_METRICS = None
_METRICS_LOCK = threading.Lock()


def _rollout_metrics():
    """Process-wide rollout counters (utils.metrics registry →
    controller KV → dashboard /metrics), tagged per worker — the PR 3
    serve_llm_* pattern applied to the RLHF sampling side."""
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            from ray_tpu.utils import metrics as um

            tk = ("worker",)
            _METRICS = {
                "groups": um.get_or_create(
                    um.Counter, "rl_rollout_groups",
                    "GRPO prompt groups generated", tk),
                "tokens": um.get_or_create(
                    um.Counter, "rl_rollout_tokens",
                    "Completion tokens generated for RLHF", tk),
                "hit_rate": um.get_or_create(
                    um.Gauge, "rl_rollout_prefix_hit_rate",
                    "Rollout prompt tokens served from the prefix "
                    "cache", tk),
            }
    return _METRICS


class LLMRolloutWorker:
    """One rollout actor: paged-KV engine + trajectory scoring.

    Constructor args are picklable (model name or LlamaConfig, engine
    kwargs dict, optional explicit params, cloudpickled reward_fn), so
    the same class runs in-process (bench/unit tests) or as a
    `ray_tpu.remote` actor (spawn with `max_concurrency >= 2`:
    `recv_weights` must ride a second thread while `rollout` decodes).
    """

    def __init__(self, model: Any = "debug", *, params: Any = None,
                 seed: int = 0, engine: dict | None = None,
                 reward_fn: Callable | None = None,
                 name: str = "rollout"):
        from ray_tpu.models import llama
        from ray_tpu.serve.llm import LLMEngine

        cfg = llama.llama_configs()[model] if isinstance(model, str) \
            else model
        ekw = dict(max_batch=8, max_len=min(cfg.max_seq, 1024),
                   page_size=64, steps_per_sync=4)
        ekw.update(engine or {})
        self.cfg = cfg
        self.name = name
        self.engine = LLMEngine(cfg, params, seed=seed, name=name,
                                **ekw)
        self.engine.start()
        self._reward = reward_fn or near_token_reward(
            cfg.vocab_size // 3, cfg.vocab_size)
        self.rollout_groups = 0
        self.rollout_completions = 0
        self.rollout_tokens = 0
        # Scoring program: one compile per (pow2 batch, pow2 length)
        # bucket, same discipline as the engine's prefill buckets.
        import jax

        self._score = jax.jit(
            lambda p, t: llama.token_logprobs(p, t, cfg))

    # ------------------------------------------------------ collective
    def init_collective_group(self, world_size: int, rank: int,
                              backend: str = "object_store",
                              group_name: str = "default") -> None:
        """Join the trainer's weight-broadcast group (the
        create_collective_group contract)."""
        from ray_tpu import collective

        collective.init_collective_group(world_size, rank, backend,
                                         group_name)

    def deregister_collective_group(self, group_name: str) -> None:
        """Drop this process's state for a stale weight-sync epoch
        (op/prefetch thread pools; the trainer reaps the rendezvous
        actor itself)."""
        from ray_tpu import collective

        collective.deregister_collective_group(group_name)

    def recv_weights(self, version: int, group_name: str,
                     src_rank: int = 0) -> int:
        """Receive one weight broadcast (ring/tree schedule, ONE packed
        transport — collective.broadcast_pytree) and stage it on the
        engine.  The unpack template is shape/dtype-only (np.empty), so
        no device fetch of the resident params is paid per sync.
        Returns the staged version; decode keeps running throughout —
        the engine swaps between sync windows."""
        from ray_tpu import collective

        template = self._params_template()
        tree = collective.broadcast_pytree(template, src_rank,
                                           group_name)
        return self.engine.update_weights(tree, version)

    def _params_template(self):
        import jax

        return jax.tree.map(
            lambda a: np.empty(a.shape, a.dtype), self.engine.params)

    def update_weights(self, refs, version: int | None = None) -> int:
        """Direct (object-plane) weight push — the no-collective path
        the trainer uses in local mode and to bootstrap replacement
        workers."""
        return self.engine.update_weights(refs, version)

    # --------------------------------------------------------- rollout
    def rollout(self, prompts: list, *, group_size: int = 4,
                max_new_tokens: int = 8, temperature: float = 1.0,
                eos_id: int | None = None) -> dict:
        """Generate a GRPO group of `group_size` completions per prompt
        and score them.  Returns the trajectory batch (numpy):

          tokens   [B, T]   prompt+completion ids, zero right-padded
          logprobs [B, T-1] behavior logprobs (valid under mask)
          mask     [B, T-1] 1.0 on completion-token positions
          prompt_len/total_len [B], rewards [B] (group-major: the K
          completions of prompt j occupy rows j*K..(j+1)*K-1)

        plus weight_version (the engine's resident policy version when
        scoring ran), gen_s, rollout_tokens, and the rollout's prefix
        hit/prefill token deltas (the group-sharing proof)."""
        from ray_tpu import failpoints

        if failpoints.ACTIVE:
            failpoints.fire("rl.rollout_step")
        if group_size < 1:
            raise ValueError(f"group_size must be >= 1, got {group_size}")
        eng = self.engine
        hit0 = eng.stats().get("prefix_hit_tokens", 0)
        pre0 = eng.prefill_tokens
        t0 = time.perf_counter()
        # Leader/followers split: the radix cache commits a prompt's
        # blocks when a request FINISHES, so a whole group submitted at
        # once would prefill the shared prompt K times.  One leader per
        # prompt prefills and commits it (all prompts' leaders run
        # concurrently); the K-1 followers then prefix-hit those blocks
        # — group rollouts cost ~one prompt prefill + K decode streams.
        leader_futs = [eng.submit(
            list(p), max_new_tokens=max_new_tokens,
            temperature=temperature, eos_id=eos_id) for p in prompts]
        leader_outs = [f.result(timeout=600) for f in leader_futs]
        follower_futs = [
            [eng.submit(list(p), max_new_tokens=max_new_tokens,
                        temperature=temperature, eos_id=eos_id)
             for _ in range(group_size - 1)] for p in prompts]
        outs = []
        for j in range(len(prompts)):
            outs.append(leader_outs[j])
            outs.extend(f.result(timeout=600)
                        for f in follower_futs[j])
        gen_s = time.perf_counter() - t0
        # Score under the CURRENT resident tree: one consistent
        # (params, version) pair — the engine publishes both under its
        # weights lock, so the trajectory's weight_version can never
        # label logprobs scored under a different tree.  With live
        # sync on, later windows of a completion may already be newer
        # than its first; max_weight_lag bounds that staleness.
        params, version = eng.params_snapshot()
        seqs, plens = [], []
        for j, prompt in enumerate(prompts):
            for k in range(group_size):
                seqs.append(list(prompt)
                            + outs[j * group_size + k]["tokens"])
                plens.append(len(prompt))
        B = len(seqs)
        tlens = [len(s) for s in seqs]
        Tp = _pow2(max(tlens))
        Bp = _pow2(B, lo=1)
        toks = np.zeros((Bp, Tp), np.int32)
        for i, s in enumerate(seqs):
            toks[i, :len(s)] = s
        logp = np.asarray(self._score(params, toks))[:B]   # [B, Tp-1]
        prompt_len = np.asarray(plens, np.int32)
        total_len = np.asarray(tlens, np.int32)
        # Completion token at absolute position j scores at column j-1.
        cols = np.arange(Tp - 1)[None, :]
        mask = ((cols >= (prompt_len - 1)[:, None])
                & (cols < (total_len - 1)[:, None])).astype(np.float32)
        rewards = np.asarray(
            [self._reward(prompts[i // group_size],
                          seqs[i][plens[i]:]) for i in range(B)],
            np.float32)
        new_tokens = int(total_len.sum() - prompt_len.sum())
        self.rollout_groups += len(prompts)
        self.rollout_completions += B
        self.rollout_tokens += new_tokens
        s = eng.stats()
        hit = s.get("prefix_hit_tokens", 0) - hit0
        prefilled = eng.prefill_tokens - pre0
        seen = hit + prefilled
        try:
            m = _rollout_metrics()
            tags = {"worker": self.name}
            m["groups"].inc(len(prompts), tags)
            m["tokens"].inc(new_tokens, tags)
            m["hit_rate"].set(hit / seen if seen else 0.0, tags)
        except Exception:  # noqa: BLE001 - metrics must never fail a rollout
            pass
        return {
            "tokens": toks[:B], "logprobs": logp, "mask": mask,
            "prompt_len": prompt_len, "total_len": total_len,
            "rewards": rewards, "group_size": group_size,
            "weight_version": version, "gen_s": gen_s,
            "rollout_tokens": new_tokens,
            "prefix_hit_tokens": hit, "prefill_tokens": prefilled,
        }

    # ----------------------------------------------------------- admin
    def stats(self) -> dict:
        return {
            "rollout_groups": self.rollout_groups,
            "rollout_completions": self.rollout_completions,
            "rollout_tokens": self.rollout_tokens,
            "weight_version": self.engine.weight_version,
            "engine": self.engine.stats(),
        }

    def kv_check(self) -> dict:
        """Zero-leaked-KV probe (chaos suites): raises on any block
        accounting inconsistency."""
        return self.engine.kv_check()

    def pid(self) -> int:
        import os

        return os.getpid()

    def stop(self) -> None:
        self.engine.stop()
