"""IMPALA: importance-weighted actor-learner with V-trace correction.

Analog of ray: rllib/algorithms/impala/ (IMPALA / IMPALAConfig; V-trace in
rllib/algorithms/impala/vtrace_torch.py semantics).  TPU-native shape: the
V-trace backward recursion is a `jax.lax.scan` over the time axis (no
Python loop under jit), batched over fragments, so the whole off-policy
update compiles to one XLA program on the learner.

Env runners keep sampling with slightly stale params (the reference's
async actor-learner decoupling); the behaviour log-probs shipped with each
fragment drive the importance ratios.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.vtrace_clip_rho = 1.0       # rho-bar (value-fit truncation)
        self.vtrace_clip_pg_rho = 1.0    # rho-bar for the policy gradient
        self.vtrace_lambda = 1.0
        self.num_sgd_iter = 1

    def training(self, *, vf_loss_coeff=None, entropy_coeff=None,
                 vtrace_clip_rho=None, vtrace_clip_pg_rho=None,
                 vtrace_lambda=None, **kw) -> "IMPALAConfig":
        for name, v in [("vf_loss_coeff", vf_loss_coeff),
                        ("entropy_coeff", entropy_coeff),
                        ("vtrace_clip_rho", vtrace_clip_rho),
                        ("vtrace_clip_pg_rho", vtrace_clip_pg_rho),
                        ("vtrace_lambda", vtrace_lambda)]:
            if v is not None:
                setattr(self, name, v)
        super().training(**kw)
        return self


def vtrace_returns(jax, jnp, batch, values, v_next, rhos, gamma,
                   rho_bar, pg_rho_bar, lam):
    """V-trace corrected value targets + policy-gradient advantages
    (ray: vtrace_torch.py semantics as one lax.scan over time).

    Shapes [B,T]; returns (vs, pg_adv), both stop-gradient.  Shared by
    IMPALA (plain PG surrogate) and APPO (clipped PPO surrogate).
    """
    clipped_rho = jnp.minimum(rho_bar, rhos)
    cs = lam * jnp.minimum(1.0, rhos)
    discounts = gamma * (1.0 - batch["dones"])     # [B,T]
    # Any episode edge (terminal OR truncation) stops the correction
    # carry — the recursion must not couple episodes.
    carry = (1.0 - jnp.maximum(batch["dones"], batch["truncs"]))

    deltas = clipped_rho * (
        batch["rewards"] + discounts * v_next - values)

    # Backward recursion: acc_t = delta_t + disc_t*c_t*carry*acc_{t+1}
    def bwd(acc, xs):
        delta_t, disc_t, c_t, k_t = xs
        acc = delta_t + disc_t * c_t * k_t * acc
        return acc, acc

    B = values.shape[0]
    _, vs_minus_v_rev = jax.lax.scan(
        bwd, jnp.zeros((B,), values.dtype),
        (deltas.T[::-1], discounts.T[::-1], cs.T[::-1], carry.T[::-1]))
    vs = values + vs_minus_v_rev[::-1].T           # [B,T]

    # vs_{t+1}: the next row's corrected value within an episode, the
    # raw bootstrap V(next_obs) at edges / the fragment end.
    vs_shift = jnp.concatenate([vs[:, 1:], v_next[:, -1:]], axis=1)
    vs_tp1 = carry * vs_shift + (1.0 - carry) * v_next
    vs_tp1 = vs_tp1.at[:, -1].set(v_next[:, -1])
    pg_adv = jax.lax.stop_gradient(
        jnp.minimum(pg_rho_bar, rhos) *
        (batch["rewards"] + discounts * vs_tp1 - values))
    return jax.lax.stop_gradient(vs), pg_adv


class IMPALA(Algorithm):
    @staticmethod
    def loss_builder(config: dict):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl import models

        gamma = config.get("gamma", 0.99)
        rho_bar = config.get("vtrace_clip_rho", 1.0)
        pg_rho_bar = config.get("vtrace_clip_pg_rho", 1.0)
        lam = config.get("vtrace_lambda", 1.0)
        vf_coeff = config.get("vf_loss_coeff", 0.5)
        ent_coeff = config.get("entropy_coeff", 0.01)

        def loss_fn(params, batch):
            # Batch axes: [B fragments, T steps, ...] — time-major inside
            # the scan, fragment axis rides along vectorized.
            obs = batch["obs"]                      # [B,T,obs]
            B, T = obs.shape[:2]
            flat = lambda a: a.reshape((B * T,) + a.shape[2:])  # noqa: E731
            logits = models.policy_logits(params, flat(obs), jnp)
            logp_all = jax.nn.log_softmax(logits, axis=-1)
            actions = flat(batch["actions"])
            logp = jnp.take_along_axis(
                logp_all, actions[:, None], axis=-1)[:, 0].reshape(B, T)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))

            values = models.value(params, flat(obs), jnp).reshape(B, T)
            # Per-step successor values — NOT a shift of `values`: at an
            # intra-fragment episode edge the next row is a fresh episode's
            # reset obs, while next_obs[t] is the true successor state.
            v_next = models.value(
                params, flat(batch["next_obs"]), jnp).reshape(B, T)

            rhos = jnp.exp(logp - batch["logp"])           # [B,T]
            vs, pg_adv = vtrace_returns(jax, jnp, batch, values, v_next,
                                        rhos, gamma, rho_bar, pg_rho_bar,
                                        lam)
            pi_loss = -jnp.mean(logp * pg_adv)
            vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
            total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "mean_rho": jnp.mean(rhos)}
        return loss_fn

    def build_env_to_learner_pipeline(self):
        # V-trace wants the [B,T] fragment layout, not flattened rows.
        from ray_tpu.rl.connectors import (ConnectorPipelineV2,
                                           RecordEpisodeMetrics,
                                           StackFragments)

        return ConnectorPipelineV2(RecordEpisodeMetrics(),
                                   StackFragments())

    def training_step(self) -> dict:
        batch = self._collect(with_gae=False)
        metrics = self.learner_group.update(
            batch, num_sgd_iter=self.cfg.get("num_sgd_iter", 1))
        self._params_np = self.learner_group.get_params_numpy()
        return metrics


IMPALA._default_config = IMPALAConfig()
IMPALAConfig.algo_class = IMPALA
