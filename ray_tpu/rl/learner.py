"""Learner actors: jitted TPU updates on collected batches.

Analog of ray: rllib/core/learner/learner.py:114 (Learner) and
learner_group.py:83 (LearnerGroup).  The torch DDP-wrap of the reference
(torch_learner.py:254,407) becomes a jitted update function — with
multiple learners, gradients would ride a pmap/psum mesh axis; the
single-learner case jits on whatever device the actor holds (TPU under
the driver, CPU in tests).
"""
from __future__ import annotations

from typing import Any, Callable

import numpy as np

import ray_tpu
from ray_tpu.rl import models


class Learner:
    """Holds params + optimizer state; `update(batch)` is the jitted step."""

    def __init__(self, config: dict, loss_builder: Callable):
        import jax
        import optax

        self.config = config
        # Own seeded generator for minibatch shuffling: the global numpy
        # RNG would make training non-reproducible across processes.
        self._np_rng = np.random.default_rng(config.get("seed", 0) + 17)
        rng = jax.random.PRNGKey(config.get("seed", 0))
        # Algorithms with non-default param trees (e.g. SAC's twin Q +
        # temperature) ship a params_builder in the config dict.
        builder = config.get("params_builder") or (
            lambda r, od, na, hidden: models.policy_value_init(
                r, od, na, hidden=hidden))
        self.params = builder(rng, config["obs_dim"], config["n_actions"],
                              config.get("hidden", 64))
        self.tx = optax.adam(config.get("lr", 3e-4))
        self.opt_state = self.tx.init(self.params)
        loss_fn = loss_builder(config)
        # Optional jitted post-minibatch transform (e.g. polyak target
        # sync); composed into the one compiled update step.
        post = config.get("post_update_builder")
        post_fn = post(config) if post else None

        def _update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if post_fn is not None:
                params = post_fn(params)
            return params, opt_state, loss, metrics

        self._update = jax.jit(_update)

    def update(self, batch: dict, num_sgd_iter: int = 1,
               minibatch_size: int | None = None) -> dict:
        """Run SGD over the batch; returns metrics (ray: Learner.update)."""
        import jax.numpy as jnp

        n = len(batch["obs"])
        mb = minibatch_size or n
        idx_all = np.arange(n)
        last_metrics: dict = {}
        for _epoch in range(num_sgd_iter):
            self._np_rng.shuffle(idx_all)
            for s in range(0, n, mb):
                idx = idx_all[s:s + mb]
                mbatch = {k: jnp.asarray(v[idx]) for k, v in batch.items()
                          if isinstance(v, np.ndarray) and len(v) == n}
                self.params, self.opt_state, loss, metrics = self._update(
                    self.params, self.opt_state, mbatch)
                last_metrics = {k: float(v) for k, v in metrics.items()}
                last_metrics["loss"] = float(loss)
        return last_metrics

    def get_params_numpy(self) -> dict:
        return models.to_numpy(self.params)

    def set_params(self, params_np: dict) -> None:
        import jax.numpy as jnp

        import jax

        self.params = jax.tree.map(jnp.asarray, params_np)

    def get_state(self) -> dict:
        """Checkpointable state (ray: Learner.get_state)."""
        import jax

        return {"params": models.to_numpy(self.params),
                "opt_state": jax.tree.map(lambda a: np.asarray(a),
                                          self.opt_state)}


class LearnerGroup:
    """One or more Learner actors (ray: learner_group.py:83).  Multiple
    learners average gradients — here: the first learner is authoritative
    and others mirror (data-parallel learning across slices would instead
    shard the batch over a jax mesh inside ONE learner, the TPU-idiomatic
    layout)."""

    def __init__(self, config: dict, loss_builder: Callable,
                 num_learners: int = 1, num_tpus_per_learner: float = 0):
        cls = ray_tpu.remote(Learner)
        opts = {"num_cpus": 1}
        if num_tpus_per_learner:
            opts["num_tpus"] = num_tpus_per_learner
        self.learners = [cls.options(**opts).remote(config, loss_builder)
                         for _ in range(max(1, num_learners))]

    def update(self, batch: dict, **kw) -> dict:
        metrics = ray_tpu.get(
            [ln.update.remote(batch, **kw) for ln in self.learners])
        if len(self.learners) > 1:
            sync = self.learners[0].get_params_numpy.remote()
            ray_tpu.get([ln.set_params.remote(sync)
                         for ln in self.learners[1:]])
        return metrics[0]

    def get_params_numpy(self) -> dict:
        return ray_tpu.get(self.learners[0].get_params_numpy.remote())

    def get_state(self) -> dict:
        return ray_tpu.get(self.learners[0].get_state.remote())

    def stop(self) -> None:
        for ln in self.learners:
            try:
                ray_tpu.kill(ln)
            except Exception:  # noqa: BLE001
                pass
