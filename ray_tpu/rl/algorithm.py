"""Algorithm + AlgorithmConfig: the RL training driver.

Analog of ray: rllib/algorithms/algorithm.py (Algorithm.step:898,
training_step:1674) and algorithm_config.py (builder-style
AlgorithmConfig).  Algorithm subclasses ray_tpu.tune.Trainable, so
`Tuner(PPO, param_space=config.to_dict())` works exactly like the
reference's `Algorithm is a Tune Trainable` contract.
"""
from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any

import numpy as np

from ray_tpu.rl.env import make_env
from ray_tpu.rl.env_runner import EnvRunnerGroup
from ray_tpu.rl.learner import LearnerGroup
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Builder: .environment().env_runners().training().resources()
    (ray: rllib/algorithms/algorithm_config.py)."""

    algo_class: type | None = None

    def __init__(self):
        self.env = "CartPole-v1"
        self.num_env_runners = 2
        self.rollout_fragment_length = 256
        self.gamma = 0.99
        self.lr = 3e-4
        self.train_batch_size = 512
        self.num_sgd_iter = 4
        self.minibatch_size = 128
        self.hidden = 64
        self.seed = 0
        self.num_learners = 1
        self.num_tpus_per_learner = 0.0
        self.extra: dict[str, Any] = {}

    # -- builder steps ------------------------------------------------------
    def environment(self, env=None, **_kw) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        return self

    def env_runners(self, num_env_runners: int | None = None,
                    rollout_fragment_length: int | None = None,
                    **_kw) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, gamma=None, lr=None, train_batch_size=None,
                 num_sgd_iter=None, minibatch_size=None,
                 **kw) -> "AlgorithmConfig":
        for name, v in [("gamma", gamma), ("lr", lr),
                        ("train_batch_size", train_batch_size),
                        ("num_sgd_iter", num_sgd_iter),
                        ("minibatch_size", minibatch_size)]:
            if v is not None:
                setattr(self, name, v)
        self.extra.update({k: v for k, v in kw.items() if v is not None})
        return self

    def learners(self, num_learners: int | None = None,
                 num_tpus_per_learner: float | None = None,
                 **_kw) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        return self

    def debugging(self, seed: int | None = None, **_kw) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def to_dict(self) -> dict:
        d = {k: v for k, v in self.__dict__.items() if k != "extra"}
        d.update(self.extra)
        return d

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        """ray: config.build_algo()."""
        if self.algo_class is None:
            raise ValueError("config has no algo_class bound")
        return self.algo_class(config=self.to_dict())

    build_algo = build


def coerce_offline(data, keys: tuple) -> dict:
    """Offline data → numpy column dict (accepts a ray_tpu.data Dataset
    or a plain dict; shared by BC/CQL)."""
    if hasattr(data, "to_numpy"):
        data = data.to_numpy()
    dtypes = {"actions": np.int64}
    return {k: np.asarray(data[k], dtypes.get(k, np.float32))
            for k in keys}


class Algorithm(Trainable):
    """Base RL algorithm; subclasses define loss_builder() and
    training_step() (ray: algorithm.py:898 step / :1674 training_step)."""

    _default_config: AlgorithmConfig | None = None

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = (cls._default_config or AlgorithmConfig()).copy()
        cfg.algo_class = cls
        return cfg

    # -- Trainable hooks ----------------------------------------------------
    def setup(self, config: dict) -> None:
        defaults = type(self).get_default_config().to_dict()
        defaults.update(config or {})
        self.cfg = defaults
        probe = make_env(self.cfg["env"], seed=0)
        self.obs_dim = probe.obs_dim
        self.n_actions = probe.n_actions
        self.env_runner_group = EnvRunnerGroup(
            self.cfg["env"], num_env_runners=self.cfg["num_env_runners"],
            gamma=self.cfg["gamma"],
            gae_lambda=self.cfg.get("gae_lambda", 0.95))
        learner_cfg = dict(self.cfg, obs_dim=self.obs_dim,
                           n_actions=self.n_actions)
        self.learner_group = LearnerGroup(
            learner_cfg, type(self).loss_builder,
            num_learners=self.cfg["num_learners"],
            num_tpus_per_learner=self.cfg["num_tpus_per_learner"])
        self._params_np = self.learner_group.get_params_numpy()
        self._timesteps = 0
        self._episode_returns: list[float] = []
        # Env→learner connector pipeline (ray: connector_v2.py:29);
        # subclasses override build_env_to_learner_pipeline() to change
        # the batch layout (e.g. V-trace [B,T] stacking).
        self.env_to_learner = self.build_env_to_learner_pipeline()

    def build_env_to_learner_pipeline(self):
        from ray_tpu.rl.connectors import (ConcatFragments,
                                           ConnectorPipelineV2,
                                           RecordEpisodeMetrics)

        return ConnectorPipelineV2(RecordEpisodeMetrics(),
                                   ConcatFragments())

    def step(self) -> dict:
        t0 = time.perf_counter()
        metrics = self.training_step()
        recent = self._episode_returns[-100:]
        result = {
            "env_runners": {
                "episode_return_mean":
                    float(np.mean(recent)) if recent else float("nan"),
                "num_episodes": len(self._episode_returns),
            },
            "num_env_steps_sampled_lifetime": self._timesteps,
            "time_this_iter_s": time.perf_counter() - t0,
            **{f"learner/{k}": v for k, v in (metrics or {}).items()},
        }
        result["episode_return_mean"] = \
            result["env_runners"]["episode_return_mean"]
        return result

    def training_step(self) -> dict:
        raise NotImplementedError

    def _greedy_eval(self, want: int, fragment: int = 200) -> None:
        """Greedy (argmax) eval rollouts until `want` episodes complete —
        the offline algorithms' metric source (BC/CQL; no training
        data comes from these)."""
        done = 0
        for _ in range(max(1, want) * 4):
            if done >= want:
                break
            frags = self.env_runner_group.sample(
                self._params_np, fragment, epsilon=0.0)
            for b in frags:
                rets = b["episode_returns"].tolist()
                done += len(rets)
                self._episode_returns.extend(rets)

    def _collect(self, epsilon: float | None = None,
                 with_gae: bool = True) -> dict:
        from ray_tpu.rl.connectors import ConnectorCtx

        per = max(1, self.cfg["train_batch_size"]
                  // self.cfg["num_env_runners"])
        batches = self.env_runner_group.sample(
            self._params_np, per, epsilon=epsilon, with_gae=with_gae)
        return self.env_to_learner(batches, ConnectorCtx(self))

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        state = self.learner_group.get_state()
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "wb") as f:
            pickle.dump({"learner": state, "timesteps": self._timesteps},
                        f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        for ln in self.learner_group.learners:
            import ray_tpu

            ray_tpu.get(ln.set_params.remote(state["learner"]["params"]))
        self._params_np = state["learner"]["params"]
        self._timesteps = state["timesteps"]

    def cleanup(self) -> None:
        self.env_runner_group.stop()
        self.learner_group.stop()

    @staticmethod
    def loss_builder(config: dict):
        raise NotImplementedError
