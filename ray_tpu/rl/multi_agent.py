"""Multi-agent RL: env interface, env runner, and multi-policy PPO.

Analog of ray: rllib/env/multi_agent_env.py (MultiAgentEnv: dict-keyed
obs/action/reward spaces per agent) + rllib/env/multi_agent_env_runner.py
(per-agent stepping, per-POLICY batch collection via policy_mapping_fn)
+ the multi-policy training loop in rllib/algorithms/algorithm.py
(one learner per policy, ray: config.multi_agent(policies=...,
policy_mapping_fn=...)).

TPU shape: one jitted learner update PER POLICY; sampling stays on CPU
actors exactly like the single-agent path.
"""
from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import make_env, register_env
from ray_tpu.rl.learner import LearnerGroup


class MultiAgentEnv:
    """Dict-keyed multi-agent episode protocol (ray: MultiAgentEnv).

    reset() -> {agent_id: obs}
    step({agent_id: action}) ->
        (obs, rewards, terminateds, truncateds, infos)
      with per-agent dicts.  Agents whose episode ended reset inside the
      env (continuing-stream semantics; per-episode returns are reported
      by the runner); their `obs` entry is then the FRESH episode's
      observation, and infos[agent]["final_obs"] carries the true last
      observation of the ended episode (the gymnasium convention) so
      value bootstrapping through truncation stays correct.
    """

    agents: list[str] = []
    obs_dim: int = 0
    n_actions: int = 0

    def reset(self) -> dict:
        raise NotImplementedError

    def step(self, actions: dict
             ) -> tuple[dict, dict, dict, dict, dict]:
        raise NotImplementedError


class MultiCartPole(MultiAgentEnv):
    """N independent CartPoles under one multi-agent env — the standard
    correctness harness for multi-agent plumbing (each agent's stream
    must train exactly like the single-agent env would)."""

    def __init__(self, seed: int = 0, num_agents: int = 2):
        from ray_tpu.rl.env import CartPole

        self.agents = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {aid: CartPole(seed=seed + i * 101)
                      for i, aid in enumerate(self.agents)}
        self.obs_dim = CartPole.obs_dim
        self.n_actions = CartPole.n_actions

    def reset(self) -> dict:
        return {aid: env.reset() for aid, env in self._envs.items()}

    def step(self, actions: dict):
        obs, rew, term, trunc, infos = {}, {}, {}, {}, {}
        for aid, a in actions.items():
            o, r, te, tr = self._envs[aid].step(a)
            infos[aid] = {}
            if te or tr:
                infos[aid]["final_obs"] = o
                o = self._envs[aid].reset()
            obs[aid], rew[aid], term[aid], trunc[aid] = o, r, te, tr
        return obs, rew, term, trunc, infos


register_env("MultiCartPole", MultiCartPole)


class MultiAgentEnvRunner:
    """Per-agent stepping, per-policy batch collection (ray:
    multi_agent_env_runner.py).  Each agent's transition stream stays
    contiguous so GAE carries correctly; per-policy batches concatenate
    the streams of the agents the mapping assigns to that policy."""

    def __init__(self, env_name, policy_mapping: dict[str, str],
                 seed: int = 0, gamma: float = 0.99,
                 gae_lambda: float = 0.95):
        self.env = make_env(env_name, seed=seed)
        self.mapping = dict(policy_mapping)
        self.rng = np.random.default_rng(seed + 1000)
        self.gamma = gamma
        self.gae_lambda = gae_lambda
        self.obs = self.env.reset()
        self.ep_return = {aid: 0.0 for aid in self.env.agents}
        self.completed: dict[str, list] = {aid: [] for aid in
                                           self.env.agents}

    def sample(self, params_by_policy: dict, n_steps: int,
               with_gae: bool = True) -> dict:
        """n_steps env ticks -> {policy_id: batch} (+ "episode_returns"
        per batch, pooled across that policy's agents)."""
        agents = self.env.agents
        buf = {aid: {"obs": [], "actions": [], "rewards": [], "dones": [],
                     "truncs": [], "logp": [], "next_obs": []}
               for aid in agents}
        for _ in range(n_steps):
            actions = {}
            for aid in agents:
                pid = self.mapping[aid]
                logits = models.policy_logits(
                    params_by_policy[pid], self.obs[aid])
                a, logp = models.sample_action(logits, self.rng)
                actions[aid] = a
                b = buf[aid]
                b["obs"].append(self.obs[aid])
                b["actions"].append(a)
                b["logp"].append(logp)
            nxt, rew, term, trunc, infos = self.env.step(actions)
            for aid in agents:
                b = buf[aid]
                b["rewards"].append(rew[aid])
                b["dones"].append(float(term[aid]))
                b["truncs"].append(float(trunc[aid] and not term[aid]))
                # True last obs of an ended episode (NOT the reset obs):
                # GAE bootstraps V(final_obs) through truncation.
                b["next_obs"].append(
                    infos.get(aid, {}).get("final_obs", nxt[aid]))
                self.ep_return[aid] += rew[aid]
                if term[aid] or trunc[aid]:
                    self.completed[aid].append(self.ep_return[aid])
                    self.ep_return[aid] = 0.0
            self.obs = nxt

        out: dict[str, dict] = {}
        for aid in agents:
            pid = self.mapping[aid]
            b = {k: np.asarray(v, np.float32) if k not in
                 ("actions",) else np.asarray(v, np.int64)
                 for k, v in buf[aid].items()}
            b["obs"] = b["obs"].astype(np.float32)
            if with_gae:
                b.update(self._gae(params_by_policy[pid], b))
            rets = np.asarray(self.completed[aid], np.float32)
            self.completed[aid] = []
            if pid not in out:
                b["episode_returns"] = rets
                out[pid] = b
            else:
                prev = out[pid]
                out[pid] = {
                    k: np.concatenate([prev[k], b[k]]) for k in b
                    if k != "episode_returns"}
                out[pid]["episode_returns"] = np.concatenate(
                    [prev["episode_returns"], rets])
        return out

    def _gae(self, params: dict, batch: dict) -> dict:
        from ray_tpu.rl.env_runner import compute_gae

        return compute_gae(params, batch, self.gamma, self.gae_lambda)


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "MultiCartPole"
        self.policies: list[str] = ["shared"]
        self.policy_mapping: dict[str, str] | None = None  # aid -> pid

    def multi_agent(self, *, policies=None, policy_mapping=None,
                    **_kw) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = list(policies)
        if policy_mapping is not None:
            self.policy_mapping = dict(policy_mapping)
        return self


class MultiAgentPPO(Algorithm):
    """PPO over a MultiAgentEnv: one jitted learner per policy, batches
    routed by the agent→policy mapping (ray: multi-agent PPO)."""

    @staticmethod
    def loss_builder(config: dict):
        from ray_tpu.rl.ppo import PPO

        return PPO.loss_builder(config)

    def setup(self, config: dict) -> None:
        defaults = type(self).get_default_config().to_dict()
        defaults.update(config or {})
        self.cfg = defaults
        probe = make_env(self.cfg["env"], seed=0)
        if not isinstance(probe, MultiAgentEnv):
            raise TypeError(f"{self.cfg['env']} is not a MultiAgentEnv")
        self.obs_dim = probe.obs_dim
        self.n_actions = probe.n_actions
        policies = self.cfg.get("policies") or ["shared"]
        mapping = self.cfg.get("policy_mapping") or {
            aid: policies[i % len(policies)]
            for i, aid in enumerate(probe.agents)}
        unknown = set(mapping.values()) - set(policies)
        if unknown:
            raise ValueError(f"mapping targets unknown policies {unknown}")
        unmapped = set(probe.agents) - set(mapping)
        if unmapped:
            raise ValueError(
                f"agents {sorted(unmapped)} have no policy mapping; "
                f"mapped: {sorted(mapping)}")
        self._mapping = mapping
        learner_cfg = dict(self.cfg, obs_dim=self.obs_dim,
                           n_actions=self.n_actions)
        self.learner_groups = {
            pid: LearnerGroup(dict(learner_cfg, seed=self.cfg["seed"] + i),
                              type(self).loss_builder,
                              num_learners=1)
            for i, pid in enumerate(policies)}
        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self.runners = [
            runner_cls.remote(self.cfg["env"], mapping, seed=i * 7919,
                              gamma=self.cfg["gamma"],
                              gae_lambda=self.cfg.get("gae_lambda", 0.95))
            for i in range(max(1, self.cfg["num_env_runners"]))]
        self._params_np = {pid: lg.get_params_numpy()
                           for pid, lg in self.learner_groups.items()}
        self._timesteps = 0
        self._episode_returns: list[float] = []

    def training_step(self) -> dict:
        per = max(1, self.cfg["train_batch_size"] // len(self.runners))
        params_ref = ray_tpu.put(self._params_np)
        frags = ray_tpu.get([r.sample.remote(params_ref, per)
                             for r in self.runners])
        metrics: dict = {}
        for pid, lg in self.learner_groups.items():
            parts = [f[pid] for f in frags if pid in f]
            if not parts:
                continue
            for p in parts:
                self._episode_returns.extend(
                    p.pop("episode_returns").tolist())
                self._timesteps += len(p["obs"])
            batch = {k: np.concatenate([p[k] for p in parts])
                     for k in parts[0]}
            m = lg.update(batch,
                          num_sgd_iter=self.cfg["num_sgd_iter"],
                          minibatch_size=self.cfg["minibatch_size"])
            metrics.update({f"{pid}/{k}": v for k, v in (m or {}).items()})
            self._params_np[pid] = lg.get_params_numpy()
        return metrics

    def cleanup(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
        for lg in self.learner_groups.values():
            lg.stop()

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        state = {pid: lg.get_state()
                 for pid, lg in self.learner_groups.items()}
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "wb") as f:
            pickle.dump({"learners": state,
                         "timesteps": self._timesteps}, f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        for pid, lg in self.learner_groups.items():
            params = state["learners"][pid]["params"]
            for ln in lg.learners:
                ray_tpu.get(ln.set_params.remote(params))
            self._params_np[pid] = params
        self._timesteps = state["timesteps"]


MultiAgentPPO._default_config = MultiAgentPPOConfig()
MultiAgentPPOConfig.algo_class = MultiAgentPPO
