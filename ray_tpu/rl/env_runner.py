"""EnvRunner actors: CPU-side experience collection.

Analog of ray: rllib/env/single_agent_env_runner.py (EnvRunner) and
rllib/env/env_runner_group.py:71 (EnvRunnerGroup) — N actors step envs
with the latest policy params (numpy forward pass; the TPU stays busy
learning while CPU actors collect, the same split as rllib's
EnvRunnerGroup.sample + LearnerGroup.update).
"""
from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.env import make_env


class EnvRunner:
    """One sampling actor: runs an env loop with the shipped params."""

    def __init__(self, env_name, seed: int = 0, gamma: float = 0.99,
                 gae_lambda: float = 0.95):
        self.env = make_env(env_name, seed=seed)
        self.rng = np.random.default_rng(seed + 1000)
        self.gamma = gamma
        self.gae_lambda = gae_lambda
        self.obs = self.env.reset()
        self.episode_return = 0.0
        self.completed_returns: list[float] = []

    def sample(self, params: dict, n_steps: int,
               epsilon: float | None = None,
               with_gae: bool = True) -> dict:
        """Collect n_steps transitions.  With epsilon set, act
        epsilon-greedily on Q-values (DQN); otherwise sample the categorical
        policy, attaching GAE advantages when with_gae (PPO; IMPALA/SAC
        take raw fragments and correct off-policy on the learner).
        """
        obs_buf = np.zeros((n_steps, len(self.obs)), np.float32)
        act_buf = np.zeros((n_steps,), np.int64)
        rew_buf = np.zeros((n_steps,), np.float32)
        done_buf = np.zeros((n_steps,), np.float32)
        trunc_buf = np.zeros((n_steps,), np.float32)
        logp_buf = np.zeros((n_steps,), np.float32)
        next_obs_buf = np.zeros_like(obs_buf)

        for t in range(n_steps):
            obs_buf[t] = self.obs
            logits = models.policy_logits(params, self.obs)
            if epsilon is not None:
                if self.rng.random() < epsilon:
                    a = int(self.rng.integers(len(logits)))
                else:
                    a = int(np.argmax(logits))
                logp = 0.0
            else:
                a, logp = models.sample_action(logits, self.rng)
            nxt, r, terminated, truncated = self.env.step(a)
            act_buf[t], rew_buf[t], logp_buf[t] = a, r, logp
            next_obs_buf[t] = nxt
            self.episode_return += r
            done = terminated or truncated
            done_buf[t] = float(terminated)   # bootstrap through truncation
            trunc_buf[t] = float(truncated and not terminated)
            if done:
                self.completed_returns.append(self.episode_return)
                self.episode_return = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = nxt

        batch = {"obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
                 "dones": done_buf, "truncs": trunc_buf, "logp": logp_buf,
                 "next_obs": next_obs_buf}
        if epsilon is None and with_gae:
            batch.update(self._gae(params, batch))
        rets, self.completed_returns = self.completed_returns, []
        batch["episode_returns"] = np.array(rets, np.float32)
        return batch

    def _gae(self, params: dict, batch: dict) -> dict:
        return compute_gae(params, batch, self.gamma, self.gae_lambda)


def compute_gae(params: dict, batch: dict, gamma: float,
                gae_lambda: float) -> dict:
    """Generalized advantage estimation (rllib:
    connectors/learner/general_advantage_estimation.py semantics) —
    the ONE implementation shared by the single- and multi-agent
    runners."""
    v = models.value(params, batch["obs"])
    v_next = models.value(params, batch["next_obs"])
    n = len(v)
    adv = np.zeros(n, np.float32)
    last = 0.0
    for t in range(n - 1, -1, -1):
        nonterminal = 1.0 - batch["dones"][t]
        # The lambda-carry must stop at ANY episode edge (terminal or
        # truncation): the next buffer row belongs to a fresh episode.
        boundary = max(batch["dones"][t], batch["truncs"][t])
        delta = batch["rewards"][t] + \
            gamma * v_next[t] * nonterminal - v[t]
        last = delta + gamma * gae_lambda * (1.0 - boundary) * last
        adv[t] = last
    returns = adv + v
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    return {"advantages": adv.astype(np.float32),
            "value_targets": returns.astype(np.float32)}


class EnvRunnerGroup:
    """Driver-side handle to N EnvRunner actors (ray:
    env_runner_group.py:71)."""

    def __init__(self, env_name, num_env_runners: int = 2,
                 gamma: float = 0.99, gae_lambda: float = 0.95,
                 num_cpus_per_env_runner: float = 1.0):
        cls = ray_tpu.remote(EnvRunner)
        self.runners = [
            cls.options(num_cpus=num_cpus_per_env_runner).remote(
                env_name, seed=i * 7919, gamma=gamma, gae_lambda=gae_lambda)
            for i in range(num_env_runners)]

    def sample(self, params_np: dict, n_steps_per_runner: int,
               epsilon: float | None = None,
               with_gae: bool = True) -> list[dict]:
        params_ref = ray_tpu.put(params_np)     # ship once, not per runner
        return ray_tpu.get([
            r.sample.remote(params_ref, n_steps_per_runner, epsilon,
                            with_gae)
            for r in self.runners])

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
