"""RL environments: gym-style API with in-repo numpy dynamics.

Analog of the reference's env layer (ray: rllib/env/; gym envs are external
there — this environment has no gymnasium wheel, so the classic control
tasks are implemented directly with the same observation/action/reward
semantics).  Vectorized stepping matches rllib's env-runner batching
(ray: rllib/env/single_agent_env_runner.py steps a gym.vector env).
"""
from __future__ import annotations

import numpy as np


class CartPole:
    """CartPole-v1 dynamics (4-dim obs, 2 actions, 500-step cap)."""

    obs_dim = 4
    n_actions = 2
    max_steps = 500

    def __init__(self, seed: int = 0):
        self.rng = np.random.default_rng(seed)
        self.gravity = 9.8
        self.masscart, self.masspole = 1.0, 0.1
        self.length = 0.5          # half pole length
        self.force_mag = 10.0
        self.tau = 0.02
        self.x_threshold = 2.4
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.state = None
        self.t = 0

    def reset(self) -> np.ndarray:
        self.state = self.rng.uniform(-0.05, 0.05, size=4)
        self.t = 0
        return self.state.astype(np.float32)

    def step(self, action: int) -> tuple[np.ndarray, float, bool, bool]:
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta**2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self.t += 1
        terminated = bool(
            abs(x) > self.x_threshold or abs(theta) > self.theta_threshold)
        truncated = self.t >= self.max_steps
        return self.state.astype(np.float32), 1.0, terminated, truncated


_ENVS = {"CartPole-v1": CartPole}


def register_env(name: str, ctor) -> None:
    """ray: tune.register_env / rllib env registry."""
    _ENVS[name] = ctor


def make_env(name: str, seed: int = 0):
    if callable(name):
        return name(seed=seed) if _accepts_seed(name) else name()
    if name not in _ENVS:
        raise ValueError(f"unknown env {name!r}; registered: {list(_ENVS)}")
    return _ENVS[name](seed=seed)


def _accepts_seed(ctor) -> bool:
    import inspect

    try:
        return "seed" in inspect.signature(ctor).parameters
    except (TypeError, ValueError):
        return False
