"""ConnectorV2 pipelines: composable batch transforms between env
runners and learners.

Analog of ray: rllib/connectors/connector_v2.py:29 (ConnectorV2) and
connector_pipeline_v2.py (ConnectorPipelineV2).  The reference threads
episodes/batches through env-to-module and learner pipelines so
algorithms share transforms instead of re-implementing them; here each
piece is a pure callable `(batch_or_fragments, ctx) -> batch`, and the
pipeline is their composition.  Algorithms build their env→learner
pipeline in `build_env_to_learner_pipeline()`; PPO and APPO differ only
in which pieces they stack (concat for time-flattened PPO batches,
fragment-stacking for the V-trace [B,T] layout).
"""
from __future__ import annotations

from typing import Any, Callable

import numpy as np


class ConnectorCtx:
    """Per-pass context: the algorithm (for metric sinks) + scratch."""

    def __init__(self, algorithm=None):
        self.algorithm = algorithm
        self.extra: dict[str, Any] = {}


class ConnectorV2:
    """One transform in a pipeline (ray: connector_v2.py:29)."""

    def __call__(self, data, ctx: ConnectorCtx):
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__


class ConnectorPipelineV2(ConnectorV2):
    """Sequential composition with list surgery (ray:
    connector_pipeline_v2.py append/prepend/insert_before_or_after)."""

    def __init__(self, *pieces: ConnectorV2):
        self.pieces: list[ConnectorV2] = list(pieces)

    def __call__(self, data, ctx: ConnectorCtx):
        for p in self.pieces:
            data = p(data, ctx)
        return data

    def append(self, piece: ConnectorV2) -> "ConnectorPipelineV2":
        self.pieces.append(piece)
        return self

    def prepend(self, piece: ConnectorV2) -> "ConnectorPipelineV2":
        self.pieces.insert(0, piece)
        return self

    def insert_before(self, name: str,
                      piece: ConnectorV2) -> "ConnectorPipelineV2":
        self.pieces.insert(self._index(name), piece)
        return self

    def insert_after(self, name: str,
                     piece: ConnectorV2) -> "ConnectorPipelineV2":
        self.pieces.insert(self._index(name) + 1, piece)
        return self

    def remove(self, name: str) -> "ConnectorPipelineV2":
        self.pieces.pop(self._index(name))
        return self

    def _index(self, name: str) -> int:
        for i, p in enumerate(self.pieces):
            if p.name == name:
                return i
        raise ValueError(f"no connector named {name!r} in pipeline "
                         f"({[p.name for p in self.pieces]})")


# ------------------------------------------------------------- pieces
class RecordEpisodeMetrics(ConnectorV2):
    """Pop per-fragment episode returns + count env steps into the
    algorithm's metric state (ray: the metrics-logger episode connector)."""

    def __call__(self, fragments: list[dict], ctx: ConnectorCtx):
        algo = ctx.algorithm
        for b in fragments:
            if "episode_returns" in b:
                rets = b.pop("episode_returns")
                if algo is not None:
                    algo._episode_returns.extend(np.asarray(rets).tolist())
            if algo is not None:
                algo._timesteps += len(b["obs"])
        return fragments


class ConcatFragments(ConnectorV2):
    """Fragments → one time-flattened batch [N, ...] (PPO/DQN layout)."""

    def __call__(self, fragments: list[dict], ctx: ConnectorCtx):
        return {k: np.concatenate([b[k] for b in fragments])
                for k in fragments[0]}


class StackFragments(ConnectorV2):
    """Fragments → [B, T, ...] batch, one row per time-ordered fragment
    (the V-trace layout: IMPALA/APPO)."""

    def __call__(self, fragments: list[dict], ctx: ConnectorCtx):
        return {k: np.stack([b[k] for b in fragments])
                for k in fragments[0]}


class FnConnector(ConnectorV2):
    """Wrap a plain function as a pipeline piece."""

    def __init__(self, fn: Callable, name: str | None = None):
        self._fn = fn
        self._name = name or getattr(fn, "__name__", "FnConnector")

    def __call__(self, data, ctx: ConnectorCtx):
        return self._fn(data, ctx)

    @property
    def name(self) -> str:
        return self._name
