"""ray_tpu.rl: reinforcement learning (the RLlib analog, SURVEY §2.3).

EnvRunnerGroup (CPU sampling actors) + LearnerGroup (jitted TPU updates)
+ Algorithm-as-Trainable, with PPO and DQN (ray: rllib/algorithms/).
"""
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.appo import APPO, APPOConfig
from ray_tpu.rl.bc import BC, BCConfig
from ray_tpu.rl.connectors import (ConnectorCtx, ConnectorPipelineV2,
                                   ConnectorV2)
from ray_tpu.rl.cql import CQL, CQLConfig
from ray_tpu.rl.dqn import DQN, DQNConfig
from ray_tpu.rl.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rl.env import make_env, register_env
from ray_tpu.rl.env_runner import EnvRunner, EnvRunnerGroup
from ray_tpu.rl.impala import IMPALA, IMPALAConfig
from ray_tpu.rl.learner import Learner, LearnerGroup
from ray_tpu.rl.marwil import MARWIL, MARWILConfig
from ray_tpu.rl.multi_agent import (MultiAgentEnv, MultiAgentEnvRunner,
                                    MultiAgentPPO, MultiAgentPPOConfig,
                                    MultiCartPole)
from ray_tpu.rl.ppo import PPO, PPOConfig
from ray_tpu.rl.replay import ReplayBuffer
from ray_tpu.rl.rlhf import (GRPOLearner, RLHFConfig, RLHFTrainer,
                             group_advantages)
from ray_tpu.rl.rollout_llm import LLMRolloutWorker
from ray_tpu.rl.sac import SAC, SACConfig

__all__ = [
    "Algorithm", "AlgorithmConfig", "APPO", "APPOConfig",
    "ConnectorCtx", "ConnectorPipelineV2", "ConnectorV2",
    "PPO", "PPOConfig", "DQN", "DQNConfig",
    "IMPALA", "IMPALAConfig", "SAC", "SACConfig", "BC", "BCConfig",
    "CQL", "CQLConfig", "MARWIL", "MARWILConfig",
    "DreamerV3", "DreamerV3Config",
    "MultiAgentEnv", "MultiAgentEnvRunner",
    "MultiAgentPPO", "MultiAgentPPOConfig", "MultiCartPole",
    "EnvRunner", "EnvRunnerGroup", "Learner", "LearnerGroup",
    "ReplayBuffer", "make_env", "register_env",
    "RLHFConfig", "RLHFTrainer", "GRPOLearner", "LLMRolloutWorker",
    "group_advantages",
]
