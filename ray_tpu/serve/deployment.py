"""Deployment decorator + application graph.

Analog of ray: python/ray/serve/deployment.py (Deployment, @serve.deployment)
and serve/api.py:510 (serve.run builds the app graph into deployments).
`Deployment.bind()` produces an `Application` node; bound nodes appearing in
another node's init args become `DeploymentHandle`s at deploy time (model
composition, ray: serve DeploymentNode DAG).
"""
from __future__ import annotations

import inspect
from typing import Any, Callable

from ray_tpu.serve.config import (AutoscalingConfig, DeploymentConfig,
                                  autoscaling_config_from_dict)


def _wrap_function(func: Callable) -> type:
    """A function deployment becomes a class whose __call__ is the function
    (ray: serve/deployment.py function deployments)."""
    if inspect.iscoroutinefunction(func):
        class _FuncDeployment:
            async def __call__(self, *args, **kwargs):
                return await func(*args, **kwargs)
    else:
        class _FuncDeployment:
            def __call__(self, *args, **kwargs):
                return func(*args, **kwargs)

    _FuncDeployment.__name__ = getattr(func, "__name__", "func_deployment")
    return _FuncDeployment


class Deployment:
    def __init__(self, cls_or_func: Callable, name: str,
                 config: DeploymentConfig):
        self._is_function = not inspect.isclass(cls_or_func)
        self._func_or_class = cls_or_func
        self._cls = (_wrap_function(cls_or_func) if self._is_function
                     else cls_or_func)
        self.name = name
        self.config = config

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def options(self, **kwargs) -> "Deployment":
        cfg = dataclasses_replace(self.config, kwargs)
        name = kwargs.pop("name", self.name)
        return Deployment(self._func_or_class, name, cfg)

    def __repr__(self):
        return f"Deployment({self.name})"


def dataclasses_replace(config: DeploymentConfig, opts: dict) -> DeploymentConfig:
    import dataclasses

    fields = {f.name for f in dataclasses.fields(DeploymentConfig)}
    updates = {k: v for k, v in opts.items() if k in fields}
    if isinstance(updates.get("autoscaling_config"), dict):
        updates["autoscaling_config"] = autoscaling_config_from_dict(
            updates["autoscaling_config"])
    elif isinstance(updates.get("autoscaling_config"), AutoscalingConfig):
        updates["autoscaling_config"].validate()
    if updates.get("num_replicas") == "auto":
        # Same translation as the decorator: autoscaling with defaults.
        updates.setdefault(
            "autoscaling_config",
            config.autoscaling_config or AutoscalingConfig())
        updates["num_replicas"] = updates["autoscaling_config"].min_replicas
    return dataclasses.replace(config, **updates)


class Application:
    """A bound deployment graph node (ray: serve Application /
    DeploymentNode).  Children appear wherever a bound node was passed in
    init args/kwargs."""

    def __init__(self, deployment: Deployment, init_args: tuple,
                 init_kwargs: dict):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs

    def _walk(self, seen: dict) -> list["Application"]:
        """Post-order unique traversal: children before parents."""
        if id(self) in seen:
            return []
        seen[id(self)] = self
        out: list[Application] = []
        for a in list(self.init_args) + list(self.init_kwargs.values()):
            if isinstance(a, Application):
                out.extend(a._walk(seen))
        out.append(self)
        return out


def deployment(cls_or_func=None, *, name: str | None = None,
               num_replicas: int | str = 1,
               max_ongoing_requests: int = 8,
               autoscaling_config: AutoscalingConfig | dict | None = None,
               user_config: Any = None,
               health_check_period_s: float = 1.0,
               graceful_shutdown_timeout_s: float = 5.0,
               ray_actor_options: dict | None = None,
               max_queued_requests: int = -1):
    """@serve.deployment (ray: serve/api.py deployment decorator).

    num_replicas="auto" enables autoscaling with defaults (ray: serve
    num_replicas="auto").  max_queued_requests bounds the replica-side
    admission queue (-1 = 2 x max_ongoing_requests, 0 = no queue);
    beyond it requests reject early with ServeOverloadedError.
    """
    if isinstance(autoscaling_config, dict):
        autoscaling_config = autoscaling_config_from_dict(
            autoscaling_config)
    elif isinstance(autoscaling_config, AutoscalingConfig):
        autoscaling_config.validate()
    if num_replicas == "auto":
        autoscaling_config = autoscaling_config or AutoscalingConfig()
        num_replicas = autoscaling_config.min_replicas

    def wrap(target):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config,
            user_config=user_config,
            health_check_period_s=health_check_period_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            ray_actor_options=ray_actor_options or {},
            max_queued_requests=max_queued_requests)
        return Deployment(target, name or target.__name__, cfg)

    if cls_or_func is not None:
        return wrap(cls_or_func)
    return wrap
