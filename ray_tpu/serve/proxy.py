"""HTTP proxy actor: stdlib-asyncio HTTP/1.1 ingress for Serve apps.

Analog of ray: python/ray/serve/_private/proxy.py (HTTPProxy:761 is an
ASGI/uvicorn app; this environment has no uvicorn/starlette so the proxy
speaks HTTP/1.1 directly over asyncio streams — same role, same routing).
Requests are routed by longest-prefix match on the app route table polled
from the controller (ray: long-poll route-table push) and forwarded through
a DeploymentHandle to the app's ingress deployment.
"""
from __future__ import annotations

import asyncio
import dataclasses
import json
import traceback
from urllib.parse import parse_qs, unquote, urlsplit


@dataclasses.dataclass
class Request:
    """What an ingress deployment receives for an HTTP request (stand-in
    for the reference's starlette.requests.Request)."""
    method: str
    path: str
    query: dict
    headers: dict
    body: bytes = b""

    def json(self):
        return json.loads(self.body) if self.body else None

    def text(self) -> str:
        return self.body.decode("utf-8", "replace")


class ProxyActor:
    """One per NODE, like the reference (proxy.py:1130 ProxyActor) —
    created and health-reconciled by the Serve controller, pinned to its
    node with a hard NodeAffinity.  Serves HTTP/1.1 and a JSON-over-gRPC
    ingress (serve/grpc_ingress.py)."""

    def __init__(self, controller_id: str, host: str = "127.0.0.1",
                 port: int = 0, grpc_port: int = 0):
        from ray_tpu.serve.handle import DeploymentHandle

        self._controller_id = controller_id
        self._handle_cls = DeploymentHandle
        self._routes: dict[str, tuple[str, str]] = {}
        self._handles: dict[str, "DeploymentHandle"] = {}
        self._port: int | None = None
        self._grpc_port: int | None = None
        self._grpc_requested_port = grpc_port
        self._server = None
        self._grpc = None
        self._error: str | None = None
        loop = asyncio.get_running_loop()
        self._ready = asyncio.Event()
        loop.create_task(self._start(host, port))
        loop.create_task(self._poll_routes())

    def _app_handle(self, app: str, method: str | None = None,
                    stream: bool = False):
        """Cached ingress handle for an app (gRPC path).  Cached per
        (app, method, stream): a fresh handle per request would leak its
        router thread and reset the in-flight counts."""
        for _prefix, (a, ingress) in self._routes.items():
            if a == app:
                key = f"{a}/{ingress}/{method or ''}/{int(stream)}"
                handle = self._handles.get(key)
                if handle is None:
                    handle = self._handle_cls(
                        ingress, a, self._controller_id,
                        method_name=method or "__call__", stream=stream)
                    self._handles[key] = handle
                return handle
        return None

    async def _start(self, host: str, port: int) -> None:
        try:
            self._server = await asyncio.start_server(
                self._handle_conn, host, port)
            self._port = self._server.sockets[0].getsockname()[1]
        except Exception as e:  # noqa: BLE001 - bind failure must surface
            self._error = f"{type(e).__name__}: {e}"
            self._ready.set()
            return
        try:
            from ray_tpu.serve.grpc_ingress import GRPCIngress

            self._grpc = GRPCIngress(
                self._app_handle,
                lambda: sorted({a for a, _i in self._routes.values()}),
                host=host, port=self._grpc_requested_port)
            await self._grpc.start()
            self._grpc_port = self._grpc.port
        except Exception:  # noqa: BLE001 - grpc unavailable: HTTP only
            self._grpc = None
        self._ready.set()

    async def _poll_routes(self) -> None:
        from ray_tpu.actor import ActorHandle

        ctrl = ActorHandle(self._controller_id)
        while True:
            try:
                self._routes = await ctrl.get_app_routes.remote()
            except Exception:  # noqa: BLE001 - controller restarting
                pass
            await asyncio.sleep(0.5)

    async def wait_for_route(self, prefix: str, app: str,
                             timeout: float = 10.0) -> bool:
        """Block until this proxy's route table maps `prefix` to `app`
        (serve.run calls this on every live proxy so its return means
        "the app is routable", not just "deployed" — the reference gets
        the same guarantee from long-poll config push)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            got = self._routes.get(prefix)
            if got is not None and got[0] == app:
                return True
            if loop.time() >= deadline:
                return False
            await asyncio.sleep(0.05)

    async def get_port(self) -> int:
        await self._ready.wait()
        if self._port is None:
            raise RuntimeError(f"proxy failed to bind: {self._error}")
        return self._port

    async def get_grpc_port(self) -> int | None:
        await self._ready.wait()
        return self._grpc_port

    async def ready(self) -> bool:
        await self._ready.wait()
        if self._port is None:
            raise RuntimeError(f"proxy failed to bind: {self._error}")
        return True

    def _match(self, path: str) -> tuple[str, str, str] | None:
        """Longest-prefix route match → (app, ingress, stripped path)."""
        best = None
        for prefix, (app, ingress) in self._routes.items():
            norm = prefix.rstrip("/") or ""
            if path == norm or path.startswith(norm + "/") or norm == "":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, app, ingress)
        if best is None:
            return None
        norm, app, ingress = best
        return app, ingress, path[len(norm):] or "/"

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
                try:
                    method, target, _version = \
                        line.decode("latin1").strip().split(" ", 2)
                except ValueError:
                    await self._respond(writer, 400, {"error": "bad request"})
                    break
                headers = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                body = b""
                n = int(headers.get("content-length", 0) or 0)
                if n:
                    body = await reader.readexactly(n)
                keep = headers.get("connection", "keep-alive") != "close"
                await self._dispatch(writer, method, target, headers, body)
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _dispatch(self, writer, method: str, target: str,
                        headers: dict, body: bytes) -> None:
        parts = urlsplit(target)
        path = unquote(parts.path)
        if path == "/-/healthz":
            await self._respond(writer, 200, "ok")
            return
        if path == "/-/routes":
            await self._respond(
                writer, 200,
                {p: f"{a}:{i}" for p, (a, i) in self._routes.items()})
            return
        m = self._match(path)
        if m is None:
            await self._respond(writer, 404,
                                {"error": f"no app for path {path!r}"})
            return
        app, ingress, sub_path = m
        key = f"{app}/{ingress}"
        handle = self._handles.get(key)
        if handle is None:
            handle = self._handle_cls(ingress, app, self._controller_id)
            self._handles[key] = handle
        query = {k: v[0] if len(v) == 1 else v
                 for k, v in parse_qs(parts.query).items()}
        req = Request(method=method, path=sub_path, query=query,
                      headers=headers, body=body)
        # Streaming response (token streaming etc.): the client opts in via
        # header; each item the ingress generator yields becomes one HTTP
        # chunk (ray: serve ASGI StreamingResponse path).
        stream = (headers.get("x-serve-stream") == "1"
                  or "text/event-stream" in headers.get("accept", ""))
        try:
            if stream:
                # Cache the stream-mode handle: a fresh handle per request
                # would leak its router thread and reset inflight counts.
                skey = key + ":stream"
                shandle = self._handles.get(skey)
                if shandle is None:
                    shandle = handle.options(stream=True)
                    self._handles[skey] = shandle
                gen = shandle.remote(req)
                await self._respond_stream(writer, gen)
            else:
                result = await handle.remote(req)
                await self._respond(writer, 200, result)
        except Exception as e:  # noqa: BLE001
            await self._respond(
                writer, 500,
                {"error": f"{type(e).__name__}: {e}",
                 "traceback": traceback.format_exc()})

    async def _respond_stream(self, writer, gen) -> None:
        """Chunked transfer: one chunk per generator item, written as the
        replica produces them."""
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: application/octet-stream\r\n"
                     b"Transfer-Encoding: chunked\r\n\r\n")
        await writer.drain()
        try:
            async for item in gen:
                if isinstance(item, bytes):
                    chunk = item
                elif isinstance(item, str):
                    chunk = item.encode()
                else:
                    chunk = (json.dumps(item) + "\n").encode()
                writer.write(f"{len(chunk):x}\r\n".encode()
                             + chunk + b"\r\n")
                await writer.drain()
        except Exception as e:  # noqa: BLE001
            msg = json.dumps(
                {"error": f"{type(e).__name__}: {e}"}).encode()
            writer.write(f"{len(msg):x}\r\n".encode() + msg + b"\r\n")
        writer.write(b"0\r\n\r\n")
        await writer.drain()

    async def _respond(self, writer, status: int, payload) -> None:
        if isinstance(payload, bytes):
            body, ctype = payload, "application/octet-stream"
        elif isinstance(payload, str):
            body, ctype = payload.encode(), "text/plain; charset=utf-8"
        else:
            try:
                body = json.dumps(payload).encode()
            except TypeError:
                body = json.dumps(repr(payload)).encode()
            ctype = "application/json"
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  500: "Internal Server Error"}.get(status, "OK")
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"\r\n".encode() + body)
        await writer.drain()
