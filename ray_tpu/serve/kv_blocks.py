"""Paged KV-cache block manager: refcounted pool + radix prefix index.

The host-side half of the serve engine's paged KV memory (the device
half — page pools, block-table indirection, tail merges — lives in
ops/paged_attention.py).  Three ideas, re-shaped for this engine:

  - **Refcounted blocks with copy-on-write** (vLLM's PagedAttention
    block tables, Kwon et al. 2023): a block is one device page of KV
    rows; any number of requests may READ a block, and a writer that
    does not hold the only reference gets a private copy first
    (`cow()`), so sealed KV content is immutable while shared — the
    same rule the object arena enforces for sealed objects.
  - **Radix prefix index** (SGLang's RadixAttention, Zheng et al.
    2024): finished requests commit their full blocks into a
    block-granular radix tree keyed on the page's token ids; a new
    request's longest cached prefix maps straight onto existing blocks
    and prefill runs only on the suffix.
  - **No implicit eviction of in-use blocks**: cached leaves are
    LRU-evicted ONLY at refcount 0 — a block some request still reads
    is never dropped, matching the arena's no-implicit-eviction
    invariant (spill, don't drop).  Eviction is leaf-first so the tree
    path above any referenced block stays matchable.

Every block id is exactly one of: on the FREE list, or MANAGED
(refcount > 0, cached in the tree, or both).  `check()` asserts this
partition — the allocator-hammer test calls it after every op.  Block
id 0 is the device trash page and is never managed here.

Pure host Python (no jax): unit-testable without a device, and every
decision (free-list order, LRU clock, eviction tie-breaks) is
deterministic so the engine's preemption behavior is replayable under
seeded tests.  Public methods lock internally: the engine loop owns all
mutations, but stats()/check() may be called from replica threads
(serve state API probes) while the tree is being rewritten.
"""
from __future__ import annotations

import functools
import threading

from ray_tpu.serve.kv_router import ROOT_HASH, chain_hash, summary_digest


def _locked(fn):
    """Serialize a public method on the manager's RLock (reentrant:
    allocate → _evict_one → …, cow → allocate compose)."""
    @functools.wraps(fn)
    def inner(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)
    return inner


class _Node:
    """One cached block: a radix-tree edge labeled by its page's token
    ids.  Children keyed by the next page's token tuple.  `hash` is the
    chained prefix hash (kv_router.chain_hash over the parent's hash +
    this page's token ids): membership of the hash alone proves the
    whole path root..node is cached — the unit the cluster router's
    prefix summaries are built from.

    Multi-LoRA keying: a non-zero `salt` (the adapter's identity salt,
    serve/lora.adapter_salt over (model_id, weight version)) prefixes
    the FIRST chunk's key, so one tree holds per-adapter subtrees whose
    chained hashes are automatically adapter-distinct — base and
    adapter KV for the same tokens never alias, and an adapter
    re-upload (new version → new salt) invalidates exactly its own
    entries by unreachability.  `toks` keeps the pure token tuple (the
    demotion path reassembles prompts from it — `key` may carry the
    salt prefix)."""

    __slots__ = ("key", "block", "parent", "children", "last_used",
                 "hash", "toks", "salt")

    def __init__(self, key: tuple | None, block: int,
                 parent: "_Node | None", *, toks: tuple | None = None,
                 salt: int = 0):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: dict[tuple, _Node] = {}
        self.last_used = 0
        self.toks = toks if toks is not None else key
        self.salt = salt
        self.hash = ROOT_HASH if parent is None \
            else chain_hash(parent.hash, key)


class BlockManager:
    """Host-side allocator + prefix index over `n_blocks` device pages
    of `page` tokens each (ids 1..n_blocks; id 0 = trash page)."""

    def __init__(self, n_blocks: int, page: int, *,
                 prefix_cache: bool = True):
        if n_blocks < 1:
            raise ValueError(f"need at least 1 block, got {n_blocks}")
        if page < 1:
            raise ValueError(f"page must be >= 1, got {page}")
        self.n_blocks = n_blocks
        self.page = page
        self.prefix_cache = prefix_cache
        # Guards every public method: mutations all come from the
        # engine loop, but stats()/check() arrive from replica threads.
        self._lock = threading.RLock()
        # pop() hands out 1, 2, ... in order — deterministic for tests.
        self._free = list(range(n_blocks, 0, -1))
        self._ref = [0] * (n_blocks + 1)
        self._root = _Node(None, 0, None)
        self._node_of: dict[int, _Node] = {}     # block id -> cached node
        self._clock = 0                          # logical LRU clock
        # Observability (exported via LLMEngine.stats() and the
        # Prometheus gauges in serve/llm.py).
        self.hits = 0            # match() calls that found >= 1 block
        self.misses = 0          # match() calls with chunks but no hit
        self.hit_tokens = 0      # prompt tokens served from cache
        self.evictions = 0
        self.cow_copies = 0
        self.demotions = 0       # blocks released to tier 2 (prefix store)
        # Leaf blocks with a demotion in flight (scan → export-thread
        # publish → finish): pinned via refcount, excluded from rescans.
        self._demoting: set[int] = set()
        # Memoized prefix_summary (stats() embeds it on every metrics
        # poll): rebuilt only when the cached SET changes (commit /
        # evict) — LRU-clock touches may reorder an over-cap subset,
        # which is acceptable staleness for an advisory summary.
        self._summary_cache: tuple[int, dict] | None = None

    # ------------------------------------------------------------ helpers
    def _chunks(self, tokens) -> list[tuple]:
        n = len(tokens) // self.page
        p = self.page
        return [tuple(tokens[i * p:(i + 1) * p]) for i in range(n)]

    @staticmethod
    def _keys(chunks: list[tuple], salt: int) -> list[tuple]:
        """Radix keys for a chunk list: a non-zero adapter salt
        prefixes the FIRST chunk's key (see _Node), branching the tree
        per adapter right at the root."""
        if not salt or not chunks:
            return chunks
        return [(salt,) + chunks[0]] + chunks[1:]

    @_locked
    def free_count(self) -> int:
        return len(self._free)

    @_locked
    def cached_count(self) -> int:
        return len(self._node_of)

    @_locked
    def evictable_count(self) -> int:
        """Blocks reclaimable without touching any in-use block: cached
        subtrees whose every node has refcount 0 (leaf-first eviction
        can drain exactly these)."""
        def count(node: _Node) -> tuple[int, bool]:
            total, all_free = 0, True
            for child in node.children.values():
                sub, sub_free = count(child)
                total += sub
                all_free &= sub_free
            if node is self._root:
                return total, all_free
            if all_free and self._ref[node.block] == 0:
                return total + 1, True
            return total, False

        return count(self._root)[0]

    @_locked
    def available(self) -> int:
        """Free + evictable: the admission budget the scheduler checks."""
        return len(self._free) + self.evictable_count()

    # ---------------------------------------------------------- allocate
    @_locked
    def allocate(self, n: int, *, evict: bool = True) -> list[int] | None:
        """Take `n` blocks (refcount 1 each), LRU-evicting cached
        refcount-0 leaves as needed.  Returns None (no partial effect)
        when free + evictable can't cover the request — in-use blocks
        are NEVER reclaimed; that decision (preempt) belongs to the
        scheduler, not the allocator."""
        if n <= 0:
            return []
        if len(self._free) < n:
            # Only consult the (tree-walk) evictable count when the
            # free list alone can't cover it — allocate() sits on the
            # decode hot loop and host Python is the scarce resource.
            budget = len(self._free) + (self.evictable_count()
                                        if evict else 0)
            if budget < n:
                return None
        out = []
        for _ in range(n):
            if not self._free:
                self._evict_one()
            b = self._free.pop()
            self._ref[b] = 1
            out.append(b)
        return out

    def _evict_one(self) -> None:
        """Drop the least-recently-used refcount-0 leaf."""
        victim = None
        for node in self._node_of.values():
            if node.children or self._ref[node.block] != 0:
                continue
            if victim is None or ((node.last_used, node.block)
                                  < (victim.last_used, victim.block)):
                victim = node
        if victim is None:                      # caller checked budget
            raise RuntimeError("no evictable block (allocator bug)")
        del victim.parent.children[victim.key]
        del self._node_of[victim.block]
        self._free.append(victim.block)
        self.evictions += 1
        self._summary_cache = None

    # --------------------------------------------------------- refcounts
    @_locked
    def retain(self, blocks: list[int]) -> None:
        for b in blocks:
            if self._ref[b] == 0 and b not in self._node_of:
                raise ValueError(f"retain of free block {b}")
            self._ref[b] += 1

    @_locked
    def release(self, blocks: list[int]) -> None:
        """Drop one reference per block.  A block at refcount 0 returns
        to the free list unless the radix tree caches it (then it stays
        resident but evictable — the prefix cache's whole point)."""
        for b in blocks:
            if self._ref[b] <= 0:
                raise ValueError(f"double free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0 and b not in self._node_of:
                self._free.append(b)

    @_locked
    def cow(self, b: int) -> tuple[int, bool]:
        """Writable version of block `b` for a caller holding one ref.

        Exclusive private block: returned as-is.  Shared (refcount > 1)
        or cached (tree-resident — sealed content other requests may
        match): allocate a fresh block, move the caller's ref onto it,
        and return (new_id, True) — the caller must device-copy the
        page before writing.  Returns (-1, False) when the pool can't
        supply the copy (caller backs off / preempts)."""
        if self._ref[b] == 1 and b not in self._node_of:
            return b, False
        nb = self.allocate(1)
        if nb is None:
            return -1, False
        self.release([b])
        self.cow_copies += 1
        return nb[0], True

    # ------------------------------------------------------------- radix
    @_locked
    def match(self, tokens, *, salt: int = 0) -> list[int]:
        """Longest cached prefix of `tokens` at block granularity.
        Takes one reference on every matched block (caller releases on
        finish/preempt) and touches the path's LRU clocks.  `salt`
        scopes the walk to one adapter's subtree (0 = base model)."""
        if not self.prefix_cache:
            return []
        chunks = self._chunks(tokens)
        node, out = self._root, []
        self._clock += 1
        for key in self._keys(chunks, salt):
            child = node.children.get(key)
            if child is None:
                break
            self._ref[child.block] += 1
            child.last_used = self._clock
            out.append(child.block)
            node = child
        if chunks:
            if out:
                self.hits += 1
                self.hit_tokens += len(out) * self.page
            else:
                self.misses += 1
        return out

    @_locked
    def commit(self, tokens, blocks: list[int], *, salt: int = 0) -> None:
        """Register a request's computed full blocks in the radix tree
        (called at finish/preempt, BEFORE release, so the blocks become
        cached rather than freed).  blocks[i] holds the KV of token
        chunk i; only chunks fully covered by both `tokens` and
        `blocks` are committed.  A chunk already in the tree keeps its
        existing block (ours stays private and frees on release) —
        first writer wins, duplicates never alias.  `salt` files the
        path under the adapter's subtree (0 = base model); KV computed
        under an adapter must never serve a base-model match."""
        if not self.prefix_cache:
            return
        chunks = self._chunks(tokens)[:len(blocks)]
        node = self._root
        self._clock += 1
        for i, key in enumerate(self._keys(chunks, salt)):
            child = node.children.get(key)
            if child is None:
                if blocks[i] in self._node_of:
                    # Same block under a different path would alias one
                    # page into two tree positions; stop committing.
                    break
                child = _Node(key, blocks[i], node, toks=chunks[i],
                              salt=salt)
                node.children[key] = child
                self._node_of[blocks[i]] = child
                self._summary_cache = None
            child.last_used = self._clock
            node = child

    @_locked
    def flush(self) -> int:
        """Invalidate the ENTIRE prefix cache (the live weight-sync
        hook: every cached page holds KV computed under the OLD policy
        — matching it after a param swap would silently attend stale
        values).  Refcount-0 cached blocks return to the free list;
        still-referenced blocks are merely un-cached — their in-flight
        readers finish under the documented staleness and the block
        frees on its last release.  Returns the number of nodes
        dropped."""
        n = len(self._node_of)
        for b in self._node_of:
            if self._ref[b] == 0:
                self._free.append(b)
        self._node_of.clear()
        self._root = _Node(None, 0, None)
        self._summary_cache = None
        return n

    # ----------------------------------------------------------- cluster
    @_locked
    def demote_scan(self, *, limit: int = 2, min_idle: int = 0,
                    watermark: int = 0, exclude=()) -> list[dict]:
        """Pick cold refcount-0 LEAVES whose subtree KV should demote
        to the tier-2 prefix store (serve/prefix_store.py).  A leaf is
        cold when its LRU clock is `min_idle` ticks stale — or, under
        pool pressure (free < `watermark`), immediately: demoting the
        next eviction victim saves its KV where plain eviction would
        destroy it.  Every candidate's WHOLE path root..leaf is pinned
        (one extra ref per block) so the exporter may gather the pages
        while serving continues; the caller MUST demote_finish() each
        candidate exactly once.  `exclude` holds leaf hashes the caller
        already knows the store won't take (publish declined) — skipped
        so a disabled store doesn't re-gather the same leaves forever.
        Coldest-first, deterministic (LRU clock, then block id)."""
        pressure = len(self._free) < watermark
        cands = []
        for node in self._node_of.values():
            if node.children or self._ref[node.block] != 0:
                continue
            if node.block in self._demoting or node.hash in exclude:
                continue
            if not pressure and self._clock - node.last_used < min_idle:
                continue
            cands.append(node)
        cands.sort(key=lambda n: (n.last_used, n.block))
        out = []
        for node in cands[:limit]:
            path = []
            cur = node
            while cur is not self._root:
                path.append(cur)
                cur = cur.parent
            path.reverse()
            blocks, tokens, hashes = [], [], []
            for nd in path:
                blocks.append(nd.block)
                tokens.extend(nd.toks)
                hashes.append(nd.hash)
            self.retain(blocks)
            self._demoting.add(node.block)
            out.append({"leaf": node.block, "blocks": blocks,
                        "tokens": tokens, "hashes": hashes,
                        "hash": node.hash, "depth": len(blocks),
                        "salt": node.salt})
        return out

    @_locked
    def demote_finish(self, leaf: int, blocks: list[int], *,
                      drop: bool) -> int:
        """Complete one demotion: release the scan's pins and — when
        the store took the entry (`drop`) — evict the maximal cold
        suffix of the path: the leaf plus every ancestor left
        childless at refcount 0 (exactly the blocks the sealed entry
        covers; hotter ancestors, referenced blocks and nodes that
        grew children mid-demotion stay in tier 1).  Returns the number
        of blocks freed.  Safe from any thread (the export thread calls
        it); a weight-swap flush mid-demotion leaves nothing to drop —
        release() already freed the pinned blocks the flush un-cached."""
        self._demoting.discard(leaf)
        self.release(blocks)
        if not drop:
            return 0
        node = self._node_of.get(leaf)
        freed = 0
        while (node is not None and node is not self._root
               and not node.children and self._ref[node.block] == 0):
            parent = node.parent
            del parent.children[node.key]
            del self._node_of[node.block]
            self._free.append(node.block)
            self.demotions += 1
            freed += 1
            self._summary_cache = None
            node = parent
        return freed

    @_locked
    def export_blocks(self, pages: list[int], n_valid_tokens: int,
                      ) -> list[int]:
        """Pin the blocks covering the first `n_valid_tokens` positions
        for a KV export: takes one extra reference on each covered
        block so the exporter may read their device pages while the
        owning request independently commits/releases, and returns the
        covered ids in table order.  Caller MUST release() them once
        the copy is sealed (the serve migration path — see
        LLMEngine.kv_export)."""
        n = -(-n_valid_tokens // self.page)
        if n > len(pages):
            raise ValueError(
                f"export of {n_valid_tokens} tokens needs {n} blocks "
                f"but the request holds {len(pages)}")
        blocks = list(pages[:n])
        self.retain(blocks)
        return blocks

    @_locked
    def prefix_summary(self, cap: int = 2048) -> dict:
        """Compact description of the cached radix tree for the cluster
        router: the chained prefix hashes of (up to `cap`, newest-LRU
        first) cached nodes plus an order-independent XOR digest.  A
        router holding this set can compute a prompt's matched-prefix
        depth without talking to the replica (kv_router.matched_depth).
        The digest changes whenever the cached set changes — the cheap
        'did serving alter the cache' probe the state API exposes.
        Memoized until commit/evict alters the set (every metrics poll
        embeds this; rebuilding per poll would tax the legacy metrics
        path even with the router switched off)."""
        if self._summary_cache is not None \
                and self._summary_cache[0] == cap:
            return self._summary_cache[1]
        nodes = self._node_of.values()
        if len(nodes) > cap:
            nodes = sorted(nodes, key=lambda n: (-n.last_used, n.block))
            nodes = nodes[:cap]
        hashes = [n.hash for n in nodes]
        # Only set-derived fields belong here: anything tracking the
        # free list would go stale under the memoization.
        out = {"page": self.page, "hashes": hashes,
               "digest": summary_digest(hashes),
               "cached": len(self._node_of)}
        self._summary_cache = (cap, out)
        return out

    # ------------------------------------------------------------ checks
    @_locked
    def check(self) -> None:
        """Assert the block-state partition (test hook): every id is
        exactly one of free / managed; refcounts non-negative; the tree
        and _node_of agree."""
        if len(set(self._free)) != len(self._free):
            raise AssertionError("duplicate ids on the free list")
        seen = set()
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            if node.block in seen:
                raise AssertionError(f"block {node.block} twice in tree")
            seen.add(node.block)
            if self._node_of.get(node.block) is not node:
                raise AssertionError(f"_node_of stale for {node.block}")
            stack.extend(node.children.values())
        if seen != set(self._node_of):
            raise AssertionError("_node_of does not match the tree")
        for b in range(1, self.n_blocks + 1):
            free = b in self._free and self._free.count(b) == 1
            managed = self._ref[b] > 0 or b in self._node_of
            if self._ref[b] < 0:
                raise AssertionError(f"negative refcount on {b}")
            if free == managed:
                raise AssertionError(
                    f"block {b}: free={free} managed={managed} "
                    f"(ref={self._ref[b]}, cached={b in self._node_of})")

    @_locked
    def stats(self) -> dict:
        return {
            "n_blocks": self.n_blocks,
            "page": self.page,
            "free": len(self._free),
            "cached": len(self._node_of),
            "evictable": self.evictable_count(),
            "hits": self.hits,
            "misses": self.misses,
            "hit_tokens": self.hit_tokens,
            "evictions": self.evictions,
            "cow_copies": self.cow_copies,
            "demotions": self.demotions,
            # The cluster router's view of this cache (compiled by the
            # DeploymentHandle via controller replica_metrics).
            "prefix_summary": self.prefix_summary(),
        }
