"""Serve configuration dataclasses.

Analog of ray: python/ray/serve/config.py + serve/schema.py (DeploymentSchema,
AutoscalingConfig) — the declarative spec the controller reconciles against
(ray: _private/deployment_state.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class AutoscalingConfig:
    """Scale replicas on ongoing-request load AND SLO attainment (ray:
    serve/config.py AutoscalingConfig; policy in
    _private/autoscaling_state.py + serve/slo.py here).

    target_ongoing_requests: per-replica load the autoscaler steers toward.
    target_p99_ttft_ms / target_queue_wait_ms: optional SLO targets — a
    sustained p99 breach scales OUT past the load-based answer, and a
    near-breach blocks downscale (see slo.slo_desired).
    """
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    metrics_interval_s: float = 0.2
    target_p99_ttft_ms: float | None = None
    target_queue_wait_ms: float | None = None

    def desired(self, total_ongoing: float, current: int) -> int:
        if current == 0:
            return max(self.min_replicas, 1)
        want = total_ongoing / self.target_ongoing_requests
        import math

        want = math.ceil(want) if want > current else math.floor(want)
        return max(self.min_replicas, min(self.max_replicas, int(want)))

    def validate(self, where: str = "autoscaling_config") -> None:
        """Field-naming validation (deploy-time: serve/schema.py and the
        @serve.deployment decorator both call this — a bad config must
        fail at validation, not at the controller's first decision)."""
        if self.min_replicas < 1:
            raise ValueError(
                f"{where}.min_replicas must be >= 1, got "
                f"{self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"{where}.max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})")
        if not self.target_ongoing_requests > 0:
            raise ValueError(
                f"{where}.target_ongoing_requests must be > 0, got "
                f"{self.target_ongoing_requests}")
        for name in ("upscale_delay_s", "downscale_delay_s",
                     "metrics_interval_s"):
            v = getattr(self, name)
            if v < 0:
                raise ValueError(f"{where}.{name} must be >= 0, got {v}")
        for name in ("target_p99_ttft_ms", "target_queue_wait_ms"):
            v = getattr(self, name)
            if v is not None and not v > 0:
                raise ValueError(
                    f"{where}.{name} must be > 0 when set, got {v}")


def autoscaling_config_from_dict(d: dict,
                                 where: str = "autoscaling_config"
                                 ) -> AutoscalingConfig:
    """dict → validated AutoscalingConfig with field-naming errors
    (unknown keys, min>max, non-positive targets) — the one conversion
    path shared by schema.py, deployment.py, and dataclasses_replace."""
    fields = {f.name for f in dataclasses.fields(AutoscalingConfig)}
    unknown = set(d) - fields
    if unknown:
        raise ValueError(
            f"unknown {where} keys {sorted(unknown)}; valid: "
            f"{sorted(fields)}")
    cfg = AutoscalingConfig(**d)
    cfg.validate(where)
    return cfg


@dataclasses.dataclass
class DeploymentConfig:
    """Per-deployment settings (ray: serve/config.py DeploymentConfig).

    max_queued_requests: replica-side admission queue bound (requests
    waiting past max_ongoing_requests); beyond it the replica rejects
    early with ServeOverloadedError instead of queueing unboundedly.
    -1 = default bound of 2 x max_ongoing_requests; 0 = no queue.
    """
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    autoscaling_config: AutoscalingConfig | None = None
    user_config: Any = None
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 5.0
    ray_actor_options: dict = dataclasses.field(default_factory=dict)
    max_queued_requests: int = -1


# Replica lifecycle states (ray: _private/common.py ReplicaState).
REPLICA_STARTING = "STARTING"
REPLICA_RUNNING = "RUNNING"
REPLICA_STOPPING = "STOPPING"


@dataclasses.dataclass
class ReplicaInfo:
    replica_id: str
    deployment: str
    app: str
    actor_id: str
    state: str = REPLICA_STARTING
    version: str = ""
