"""Serve configuration dataclasses.

Analog of ray: python/ray/serve/config.py + serve/schema.py (DeploymentSchema,
AutoscalingConfig) — the declarative spec the controller reconciles against
(ray: _private/deployment_state.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class AutoscalingConfig:
    """Scale replicas on ongoing-request load (ray: serve/config.py
    AutoscalingConfig; policy in _private/autoscaling_state.py).

    target_ongoing_requests: per-replica load the autoscaler steers toward.
    """
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    metrics_interval_s: float = 0.2

    def desired(self, total_ongoing: float, current: int) -> int:
        if current == 0:
            return max(self.min_replicas, 1)
        want = total_ongoing / self.target_ongoing_requests
        import math

        want = math.ceil(want) if want > current else math.floor(want)
        return max(self.min_replicas, min(self.max_replicas, int(want)))


@dataclasses.dataclass
class DeploymentConfig:
    """Per-deployment settings (ray: serve/config.py DeploymentConfig)."""
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    autoscaling_config: AutoscalingConfig | None = None
    user_config: Any = None
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 5.0
    ray_actor_options: dict = dataclasses.field(default_factory=dict)


# Replica lifecycle states (ray: _private/common.py ReplicaState).
REPLICA_STARTING = "STARTING"
REPLICA_RUNNING = "RUNNING"
REPLICA_STOPPING = "STOPPING"


@dataclasses.dataclass
class ReplicaInfo:
    replica_id: str
    deployment: str
    app: str
    actor_id: str
    state: str = REPLICA_STARTING
    version: str = ""
