"""Public Serve API: start/run/status/delete/shutdown + handles.

Analog of ray: python/ray/serve/api.py (serve.run:510, serve.start,
serve.status, serve.delete, serve.shutdown, serve.get_app_handle).
"""
from __future__ import annotations

import hashlib
import logging
from typing import Any

import cloudpickle

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle

logger = logging.getLogger(__name__)

PROXY_NAME = "SERVE_PROXY"

_controller = None      # ActorHandle
_proxy = None           # ActorHandle


class HTTPOptions(dict):
    """serve.start(http_options=...) options (ray: serve.HTTPOptions).
    A dict subclass so the existing dict-based plumbing accepts it
    unchanged; attribute access mirrors the reference dataclass."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 **extra: Any):
        super().__init__(host=host, port=port, **extra)

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None


def get_replica_context():
    """Identity of the replica this code runs in (ray:
    serve.get_replica_context): .app_name, .deployment, .replica_tag,
    .servable_object.  Raises outside a replica."""
    from ray_tpu.serve import replica as _replica

    ctx = _replica.get_current_context()
    if ctx is None:
        raise RuntimeError(
            "get_replica_context() may only be called inside a "
            "deployment replica")
    return ctx


def start(http_options: dict | None = None, detached: bool = True):
    """Ensure the Serve instance (controller + one proxy PER NODE) is
    running (ray: serve.start; proxies are reconciled by the controller
    like the reference's proxy_state machinery)."""
    global _controller
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    import time as _time

    if _controller is None:
        _controller = ray_tpu.remote(ServeController).options(
            name=CONTROLLER_NAME, get_if_exists=True, lifetime="detached",
            max_concurrency=32, num_cpus=0.1).remote()
    if http_options:
        # Only explicit options overwrite the stored ones: a bare
        # start() (e.g. from serve.run) must not reset a configured
        # port back to defaults.
        ray_tpu.get(_controller.set_http_options.remote(
            http_options.get("host", "127.0.0.1"),
            http_options.get("port", 0)), timeout=60.0)
    # Wait for at least one proxy to come up (the controller's reconcile
    # loop creates one per alive node).  Probe EVERY listed proxy — one
    # stuck proxy must not mask a healthy one on another node.
    deadline = _time.monotonic() + 60.0
    while _time.monotonic() < deadline:
        names = ray_tpu.get(_controller.list_proxies.remote(),
                            timeout=30.0)
        for name in names:
            try:
                h = ray_tpu.get_actor(name)
                ray_tpu.get(h.ready.remote(), timeout=10.0)
                return _controller
            except Exception:  # noqa: BLE001 - proxy restarting
                continue
        _time.sleep(0.2)
    raise TimeoutError("no serve proxy became ready in 60s")


def _deployment_version(app_node: Application) -> str:
    """Code/config version: changing only user_config or num_replicas keeps
    the version → in-place reconfigure/scale instead of replica restart
    (ray: deployment_state.py version/config-change classification)."""
    d = app_node.deployment
    payload = cloudpickle.dumps((
        d._cls, app_node.init_args, app_node.init_kwargs,
        d.config.max_ongoing_requests, d.config.ray_actor_options))
    return hashlib.sha1(payload).hexdigest()[:12]


def run(app: Application, *, name: str = "default",
        route_prefix: str = "/", _blocking: bool = True,
        timeout_s: float = 120.0) -> DeploymentHandle:
    """Deploy an application graph and return a handle to its ingress
    (ray: serve.run api.py:510)."""
    if not isinstance(app, Application):
        raise TypeError("serve.run takes the result of Deployment.bind()")
    ctrl = start()
    nodes = app._walk({})
    names = set()
    for node in nodes:
        if node.deployment.name in names:
            raise ValueError(
                f"duplicate deployment name {node.deployment.name!r} in app")
        names.add(node.deployment.name)

    deployments = []
    for node in nodes:
        # Replace bound child nodes with handles (model composition).
        def sub(v):
            if isinstance(v, Application):
                return DeploymentHandle(v.deployment.name, name,
                                        ctrl.actor_id)
            return v
        deployments.append({
            "name": node.deployment.name,
            "cls": node.deployment._cls,
            "init_args": tuple(sub(a) for a in node.init_args),
            "init_kwargs": {k: sub(v) for k, v in node.init_kwargs.items()},
            "config": node.deployment.config,
            "version": _deployment_version(node),
        })
    ray_tpu.get(ctrl.deploy_app.remote(
        name, route_prefix, app.deployment.name, deployments), timeout=60.0)
    if _blocking:
        ok = ray_tpu.get(ctrl.wait_for_deployments_ready.remote(
            name, timeout_s), timeout=timeout_s + 10.0)
        if not ok:
            raise TimeoutError(
                f"app {name!r} did not become ready in {timeout_s}s: "
                f"{status()}")
        # Ready means replicas are up; proxies learn routes on a poll.
        # Block until every live proxy routes this app so an HTTP request
        # issued right after run() cannot 404 (best effort: a proxy that
        # appears later catches up on its own poll).
        waits = []
        for pname in ray_tpu.get(ctrl.list_proxies.remote(), timeout=30.0):
            try:
                waits.append(ray_tpu.get_actor(pname)
                             .wait_for_route.remote(route_prefix, name))
            except Exception:  # noqa: BLE001 - proxy died; reconcile redoes
                pass
        if waits:
            try:
                ray_tpu.get(waits, timeout=15.0)
            except Exception:  # noqa: BLE001 - don't fail run() on a proxy
                pass
    return DeploymentHandle(app.deployment.name, name, ctrl.actor_id)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    ctrl = _require_controller()
    st = ray_tpu.get(ctrl.status.remote())
    if name not in st:
        raise ValueError(f"no serve app named {name!r}")
    ingress = ray_tpu.get(ctrl.get_app_routes.remote())
    for _prefix, (app, ing) in ingress.items():
        if app == name:
            return DeploymentHandle(ing, name, ctrl.actor_id)
    raise ValueError(f"app {name!r} has no ingress")


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    ctrl = _require_controller()
    return DeploymentHandle(deployment_name, app_name, ctrl.actor_id)


def status() -> dict:
    ctrl = _require_controller()
    return ray_tpu.get(ctrl.status.remote())


def replica_metrics(app_name: str | None = None,
                    deployment: str | None = None) -> dict:
    """Per-replica metrics including the user callable's stats() dict
    (e.g. the LLM engine's prefix-cache hit/evict/preempt counters and
    the prefix-summary digest the cache-aware router consumes) —
    {app: {deployment: {replica: metrics}}}.  The state-API detail
    surface next to serve.status() (ray: serve application details)."""
    ctrl = _require_controller()
    return ray_tpu.get(
        ctrl.replica_metrics.remote(app_name, deployment=deployment),
        timeout=30.0)


def delete(name: str, _blocking: bool = True) -> None:
    ctrl = _require_controller()
    ray_tpu.get(ctrl.delete_app.remote(name))
    if _blocking:
        import time

        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if name not in ray_tpu.get(ctrl.status.remote()):
                return
            time.sleep(0.1)


def list_proxies() -> list[str]:
    """Names of the per-node proxy actors (SERVE_PROXY::<node_id>)."""
    ctrl = _require_controller()
    return ray_tpu.get(ctrl.list_proxies.remote(), timeout=30.0)


def proxy_ports() -> list[int]:
    """HTTP ports of every live per-node proxy."""
    ports = []
    for name in list_proxies():
        try:
            ports.append(ray_tpu.get(
                ray_tpu.get_actor(name).get_port.remote(), timeout=30.0))
        except Exception:  # noqa: BLE001 - proxy mid-restart
            pass
    return ports


def http_port() -> int:
    """Port of one live HTTP proxy (ephemeral by default)."""
    ports = proxy_ports()
    if not ports:
        raise RuntimeError("serve has no live proxy")
    return ports[0]


def grpc_port() -> int:
    """Port of one live gRPC ingress."""
    for name in list_proxies():
        try:
            port = ray_tpu.get(
                ray_tpu.get_actor(name).get_grpc_port.remote(),
                timeout=30.0)
            if port:
                return port
        except Exception:  # noqa: BLE001
            pass
    raise RuntimeError("serve has no live gRPC ingress")


def shutdown() -> None:
    """Tear down all apps, the controller and every proxy (ray:
    serve.shutdown)."""
    global _controller, _proxy
    if _controller is None:
        # A fresh process (e.g. `ray-tpu serve shutdown`) must still be
        # able to tear down a detached serve instance by name.
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:  # noqa: BLE001 - nothing running
            _controller = None
    if _controller is not None:
        proxy_names: list[str] = []
        try:
            proxy_names = ray_tpu.get(_controller.list_proxies.remote(),
                                      timeout=10.0)
        except Exception:  # noqa: BLE001
            pass
        try:
            ray_tpu.get(_controller.graceful_shutdown.remote(), timeout=30.0)
            import time

            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if not ray_tpu.get(_controller.status.remote()):
                    break
                time.sleep(0.1)
        except Exception:  # noqa: BLE001
            pass
        try:
            ray_tpu.kill(_controller)
        except Exception:  # noqa: BLE001
            pass
        _controller = None
        for name in proxy_names:
            try:
                ray_tpu.kill(ray_tpu.get_actor(name))
            except Exception:  # noqa: BLE001
                pass
    _proxy = None


def _require_controller():
    global _controller
    if _controller is None:
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except ValueError:
            raise RuntimeError(
                "serve is not running; call serve.start() or serve.run()")
    return _controller


def ingress(_app=None):
    """Marker decorator for API parity (ray: @serve.ingress(app) wires a
    FastAPI app; without FastAPI in this environment the ingress deployment
    receives ray_tpu.serve.Request directly)."""
    def wrap(cls):
        return cls
    return wrap if _app is None or isinstance(_app, type) else wrap
