"""gRPC ingress for Serve (ray: serve/_private/proxy.py:540 gRPCProxy).

A generic-handler gRPC server (no protoc codegen: method handlers are
registered dynamically, payloads are JSON bytes) exposing the same routing
the HTTP proxy offers:

  /ray.serve.RayTpuServe/Predict       request  {"application": ...,
                                                 "method"?: ...,
                                                 "payload": ...}
                                       response {"result": ...}
  /ray.serve.RayTpuServe/ListApplications      -> {"applications": [...]}
  /ray.serve.RayTpuServe/Healthz               -> {"status": "ok"}
  /ray.serve.RayTpuServe/PredictStreaming      server-streaming variant:
                                       one JSON message per item the
                                       replica generator yields.

The reference serves user-defined proto services through generated
descriptors; this framework's wire format is JSON-over-gRPC — the routing,
per-application dispatch, and streaming semantics match.
"""
from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Callable

import grpc

logger = logging.getLogger(__name__)

SERVICE = "ray.serve.RayTpuServe"


def _bytes_codec(x: bytes) -> bytes:
    return x


class _GenericService(grpc.GenericRpcHandler):
    def __init__(self, handlers: dict):
        self._handlers = handlers

    def service(self, handler_call_details):
        return self._handlers.get(handler_call_details.method)


class GRPCIngress:
    """Async gRPC server routing to deployment handles.

    handle_for(app_name) -> DeploymentHandle is supplied by the proxy,
    which owns the route table and handle cache.
    """

    def __init__(self, handle_for: Callable[[str], Any],
                 list_apps: Callable[[], list[str]],
                 host: str = "127.0.0.1", port: int = 0):
        self._handle_for = handle_for
        self._list_apps = list_apps
        self._server = grpc.aio.server()
        handlers = {
            f"/{SERVICE}/Predict": grpc.unary_unary_rpc_method_handler(
                self._predict, request_deserializer=_bytes_codec,
                response_serializer=_bytes_codec),
            f"/{SERVICE}/PredictStreaming":
                grpc.unary_stream_rpc_method_handler(
                    self._predict_streaming,
                    request_deserializer=_bytes_codec,
                    response_serializer=_bytes_codec),
            f"/{SERVICE}/ListApplications":
                grpc.unary_unary_rpc_method_handler(
                    self._list_applications,
                    request_deserializer=_bytes_codec,
                    response_serializer=_bytes_codec),
            f"/{SERVICE}/Healthz": grpc.unary_unary_rpc_method_handler(
                self._healthz, request_deserializer=_bytes_codec,
                response_serializer=_bytes_codec),
        }
        self._server.add_generic_rpc_handlers(
            (_GenericService(handlers),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")

    async def start(self) -> None:
        await self._server.start()

    async def stop(self) -> None:
        await self._server.stop(grace=1.0)

    # ------------------------------------------------------------ methods
    # NOTE: grpc.aio's ServicerContext.abort is a COROUTINE — an unawaited
    # abort is a silent no-op and control falls through the error branch
    # (surfaced as an UnboundLocalError when a dead-actor error hit the
    # _predict except path).  Every abort below must stay awaited.
    @staticmethod
    async def _parse(request: bytes, context) -> dict:
        try:
            req = json.loads(request.decode() or "{}")
        except json.JSONDecodeError:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "request body must be JSON")
        if not isinstance(req, dict):
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "request body must be a JSON object")
        return req

    async def _predict(self, request: bytes, context) -> bytes:
        req = await self._parse(request, context)
        app = req.get("application")
        if not app:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                'missing "application"')
        handle = self._handle_for(app, req.get("method"))
        if handle is None:
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"no application {app!r}")
        try:
            result = await handle.remote(req.get("payload"))
        except Exception as e:  # noqa: BLE001
            await context.abort(grpc.StatusCode.INTERNAL,
                                f"{type(e).__name__}: {e}")
        return json.dumps({"result": result}).encode()

    async def _predict_streaming(self, request: bytes, context):
        req = await self._parse(request, context)
        app = req.get("application")
        handle = self._handle_for(app, req.get("method"),
                                  stream=True) if app else None
        if handle is None:
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"no application {app!r}")
        try:
            gen = handle.remote(req.get("payload"))
            async for item in gen:
                yield json.dumps({"result": item}).encode()
        except Exception as e:  # noqa: BLE001
            await context.abort(grpc.StatusCode.INTERNAL,
                                f"{type(e).__name__}: {e}")

    async def _list_applications(self, request: bytes, context) -> bytes:
        return json.dumps({"applications": self._list_apps()}).encode()

    async def _healthz(self, request: bytes, context) -> bytes:
        return json.dumps({"status": "ok"}).encode()
