"""Multi-LoRA adapter registry: thousands of fine-tunes, one engine.

The million-user serving regime is many small fine-tunes time-sharing a
handful of base models (S-LoRA, Punica; the reference's Serve model
multiplexing).  This module is the OFF-ENGINE half of that subsystem:

  - **Cold adapters** live as sealed arena objects — published exactly
    like tier-2 prefixes (serve/prefix_store.py): one `ray_tpu.put` per
    adapter, memledger-tagged per tenant, paged in over the object
    plane's same-host direct-shm / cross-node streaming get path.  The
    controller-side **AdapterDirectory** indexes model_id → (ref,
    version, rank); every upload bumps the version.
  - **Hot adapters** are device-resident bank rows inside the paged
    LLMEngine (serve/llm.py): per-target [L, n_slots, din, r] /
    [L, n_slots, r, dout] stacks a per-request int32 index gathers
    inside ONE jitted decode/prefill program (models/llama._lora_proj)
    — never a retrace per adapter, and a batch freely mixes adapters.
  - **KV identity**: `adapter_salt(model_id, version)` keys the radix
    tree / prefix store / router hashes per (base seed, adapter,
    version) — an adapter re-upload or RLHF swap invalidates exactly
    its own cached KV (a new version hashes to a different subtree;
    stale entries become unreachable and LRU out).

Kill switches: RAY_TPU_LORA=0 (per request — same-run A/B; off =
requests serve the base model) and RAY_TPU_LORA_ROUTER=0 (residency
routing only — the bench's blind-routing arm).  Failpoint sites
`serve.adapter_load` / `serve.adapter_swap` are armed on the server /
engine load legs (serve/llm.py) — a load fault degrades to a typed
AdapterLoadError rejection, never a wedged engine loop.

Dependency-light by the layering invariant: core primitives + public
facades (memledger, tracing) + serve siblings (kv_router) only.
"""
from __future__ import annotations

import hashlib
import threading
import time

from ray_tpu.serve.kv_router import lora_on, lora_router_on  # noqa: F401

# Named actor the client resolves lazily (literal, NOT imported from
# serve/controller.py — the controller imports this module for its
# directory, and the reverse import would cycle).
_CONTROLLER_NAME = "SERVE_CONTROLLER"


def adapter_salt(model_id: str, version: int) -> int:
    """KV-identity salt for (adapter, version): a 63-bit blake2b int
    (non-zero; fits chain_hash's signed-8-byte token encoding) that
    prefixes the first radix chunk of every prompt served under this
    adapter — see kv_blocks._Node / kv_router.prompt_hashes.  The
    VERSION is inside the salt, so a re-upload invalidates old KV by
    unreachability rather than by scrubbing.  Process-stable (never
    `hash()`)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(model_id.encode())
    h.update(int(version).to_bytes(8, "little", signed=True))
    return (int.from_bytes(h.digest(), "little") & ((1 << 63) - 1)) or 1


def _adapter_nbytes(adapter: dict) -> int:
    n = 0
    for ab in adapter.get("targets", {}).values():
        for arr in ab.values():
            n += int(getattr(arr, "nbytes", 0))
    return n


def _validate_adapter(adapter: dict) -> int:
    """Shape-contract check at PUBLISH time (the engine re-validates
    against its own config at load).  Returns the adapter's rank."""
    if not isinstance(adapter, dict) or "targets" not in adapter:
        raise ValueError(
            "adapter must be {'rank': int, 'targets': {name: {'a', "
            "'b'}}} (see models/llama.init_lora_adapter)")
    rank = int(adapter.get("rank", 0))
    for t, ab in adapter["targets"].items():
        a, b = ab.get("a"), ab.get("b")
        if a is None or b is None:
            raise ValueError(f"adapter target {t!r} missing 'a'/'b'")
        if a.ndim != 3 or b.ndim != 3 or a.shape[2] != b.shape[1]:
            raise ValueError(
                f"adapter target {t!r}: want a [L, din, r] / "
                f"b [L, r, dout], got {a.shape} / {b.shape}")
        if rank and a.shape[2] != rank:
            raise ValueError(
                f"adapter target {t!r}: rank {a.shape[2]} != declared "
                f"rank {rank}")
        rank = rank or int(a.shape[2])
    if rank < 1:
        raise ValueError("adapter has no targets")
    return rank


class AdapterDirectory:
    """Controller-side registry of published adapters (one instance on
    the ServeController; tests may instantiate one directly and hand it
    to a LoraClient, which then calls it in-process instead of over
    RPC).  The directory holds a borrowed ref per entry — the borrow
    keeps the sealed bytes alive after the publisher drops its local
    handle, and dropping the entry (forget/clear) releases them.  The
    publisher process is still the object's OWNER (object-plane
    discipline: owner-is-truth); a publisher that exits strands its
    adapters, so long-lived tenants re-publish from a live process."""

    def __init__(self):
        self._lock = threading.Lock()
        # model_id -> {"ref", "version", "rank", "nbytes", "tenant", "t"}
        self._adapters: dict[str, dict] = {}
        self.published = 0
        self.forgotten = 0
        self.lookups = 0
        self.lookup_misses = 0

    def publish(self, model_id: str, meta: dict, ref) -> dict:
        """Register (or re-upload) one adapter.  The directory owns
        versioning: every publish of a model_id bumps its version, so
        adapter_salt(model_id, version) — and with it every cached KV
        key — rolls over atomically with the weights.

        `ref` arrives wrapped in a one-element list when it crosses the
        controller RPC: a TOP-LEVEL ObjectRef arg is resolved to its
        value before execution (worker arg semantics), which would make
        the directory hold the whole host pytree and let the arena
        object die; nested refs stay refs and register this process as
        a borrower, so the sealed bytes outlive the publisher's local
        handle and lookups stay meta-only."""
        if isinstance(ref, list):
            ref = ref[0]
        with self._lock:
            old = self._adapters.get(model_id)
            version = (old["version"] + 1) if old else 1
            self._adapters[model_id] = {
                "ref": ref,
                "version": version,
                "rank": int(meta.get("rank", 0)),
                "nbytes": int(meta.get("nbytes", 0)),
                "tenant": meta.get("tenant"),
                "t": time.monotonic(),
            }
            self.published += 1
        return {"version": version,
                "salt": adapter_salt(model_id, version)}

    def lookup(self, model_id: str) -> dict | None:
        with self._lock:
            self.lookups += 1
            e = self._adapters.get(model_id)
            if e is None:
                self.lookup_misses += 1
                return None
            return {"ref": e["ref"], "version": e["version"],
                    "rank": e["rank"], "nbytes": e["nbytes"],
                    "salt": adapter_salt(model_id, e["version"])}

    def forget(self, model_id: str) -> bool:
        with self._lock:
            e = self._adapters.pop(model_id, None)
            if e is not None:
                self.forgotten += 1
            return e is not None

    def clear(self) -> int:
        with self._lock:
            n = len(self._adapters)
            self._adapters.clear()
            self.forgotten += n
        return n

    def summary(self) -> dict:
        """model_id -> version, for state APIs / dashboards."""
        with self._lock:
            return {m: e["version"] for m, e in self._adapters.items()}

    def stats(self) -> dict:
        with self._lock:
            return {
                "adapters": len(self._adapters),
                "bytes": sum(e["nbytes"]
                             for e in self._adapters.values()),
                "published": self.published,
                "forgotten": self.forgotten,
                "lookups": self.lookups,
                "lookup_misses": self.lookup_misses,
            }


def _object_plane_ready() -> bool:
    """Same probe as prefix_store: an initialized driver OR a connected
    worker can put/get arena objects."""
    import ray_tpu

    if ray_tpu.is_initialized():
        return True
    try:
        from ray_tpu.runtime_context import get_runtime_context

        get_runtime_context()
        return True
    except Exception:  # noqa: BLE001 - no worker in this process
        return False


class LoraClient:
    """Publish / fetch half used by drivers (upload) and replicas
    (page-in).  Mirrors PrefixStoreClient's transport: an injected
    in-process AdapterDirectory (tests) or the controller's lora_*
    RPC verbs."""

    def __init__(self, directory: AdapterDirectory | None = None):
        self._directory = directory
        self._lock = threading.Lock()
        self._ctrl = None
        self._ctrl_retry_at = 0.0

    def _controller(self):
        if self._directory is not None:
            return None
        if not _object_plane_ready():
            return None
        import ray_tpu

        with self._lock:
            if self._ctrl is not None:
                return self._ctrl
            if time.monotonic() < self._ctrl_retry_at:
                return None
        try:
            ctrl = ray_tpu.get_actor(_CONTROLLER_NAME)
        except Exception:  # noqa: BLE001 - serve not running
            with self._lock:
                self._ctrl_retry_at = time.monotonic() + 5.0
            return None
        with self._lock:
            self._ctrl = ctrl
        return ctrl

    def _call(self, verb: str, *args, timeout: float = 10.0,
              default=None, **kwargs):
        if self._directory is not None:
            return getattr(self._directory, verb)(*args, **kwargs)
        ctrl = self._controller()
        if ctrl is None:
            return default
        import ray_tpu

        try:
            ref = getattr(ctrl, "lora_" + verb).remote(*args, **kwargs)
            return ray_tpu.get(ref, timeout=timeout)
        except Exception:  # noqa: BLE001 - controller restarting
            with self._lock:
                self._ctrl = None
                self._ctrl_retry_at = time.monotonic() + 5.0
            return None

    # ----------------------------------------------------------- publish
    def publish(self, model_id: str, adapter: dict, *,
                tenant: str | None = None) -> dict:
        """Seal an adapter into the object plane and register it.
        Returns {"version", "salt"}.  The arena object is tagged per
        tenant in the memory ledger (`ray-tpu memory` groups adapter
        bytes by who uploaded them)."""
        if not model_id or not isinstance(model_id, str):
            raise ValueError(f"model_id must be a non-empty string, "
                             f"got {model_id!r}")
        rank = _validate_adapter(adapter)
        meta = {"rank": rank, "nbytes": _adapter_nbytes(adapter),
                "tenant": tenant}
        if self._directory is not None and not _object_plane_ready():
            # In-process directory with no object plane (unit tests):
            # the host pytree itself is the payload.
            ref = adapter
        else:
            import ray_tpu
            from ray_tpu import memledger

            with memledger.tag("lora_adapter",
                               label=tenant or model_id):
                ref = ray_tpu.put(adapter)
        # Nest the ref (one-element list): top-level ObjectRef args are
        # resolved to values before execution, which would ship the
        # whole pytree to the controller and free the arena object —
        # nested refs stay refs and the directory borrows them.
        reply = self._call("publish", model_id, meta, [ref],
                           default=None)
        if reply is None:
            raise RuntimeError(
                f"adapter publish failed: no serve controller "
                f"reachable for {model_id!r}")
        return reply

    def delete(self, model_id: str) -> bool:
        return bool(self._call("forget", model_id, default=False))

    # ------------------------------------------------------------- fetch
    def lookup(self, model_id: str) -> dict | None:
        """Directory metadata only ({"ref", "version", "rank",
        "nbytes", "salt"}) — no payload pull, so a replica can check
        version freshness for one controller round trip and skip the
        object-plane get when the version is already resident."""
        return self._call("lookup", model_id, default=None)

    def fetch(self, model_id: str, timeout: float = 30.0) -> dict | None:
        """Resolve + pull one adapter: {"adapter": pytree, "version",
        "salt", "rank", "nbytes"} or None when the registry has no such
        model id.  Pull failures raise (the caller maps them to
        AdapterLoadError)."""
        entry = self.lookup(model_id)
        if entry is None:
            return None
        return {"adapter": resolve_entry(entry, timeout=timeout),
                "version": entry["version"], "salt": entry["salt"],
                "rank": entry["rank"], "nbytes": entry["nbytes"]}

    def summary(self) -> dict:
        return self._call("summary", default={}) or {}

    def stats(self) -> dict:
        return self._call("stats", default={}) or {}


def resolve_entry(entry: dict, timeout: float = 30.0) -> dict:
    """Pull a directory entry's adapter pytree off the object plane
    (same-host direct-shm / cross-node streaming — the normal get
    path).  Tests with an in-process directory and no object plane
    publish the host pytree itself as the ref; that passes through."""
    payload = entry["ref"]
    from ray_tpu.object_ref import ObjectRef

    if isinstance(payload, ObjectRef):
        import ray_tpu

        payload = ray_tpu.get(payload, timeout=timeout)
    return payload


_default_client: LoraClient | None = None
_default_lock = threading.Lock()


def _client() -> LoraClient:
    global _default_client
    with _default_lock:
        if _default_client is None:
            _default_client = LoraClient()
        return _default_client


def publish_adapter(model_id: str, adapter: dict, *,
                    tenant: str | None = None) -> dict:
    """Upload a LoRA adapter under `model_id` (driver-side; see
    models/llama.init_lora_adapter for the weight format).  Returns
    {"version", "salt"}.  Requests carrying {"model_id": ...} are then
    served under these weights by any lora-enabled deployment."""
    return _client().publish(model_id, adapter, tenant=tenant)


def delete_adapter(model_id: str) -> bool:
    """Withdraw an adapter from the registry.  Engines holding it
    resident keep serving in-flight requests; new loads miss."""
    return _client().delete(model_id)


def list_adapters() -> dict:
    """model_id -> version for every published adapter."""
    return _client().summary()
