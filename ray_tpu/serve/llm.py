"""Continuous-batched LLM inference engine + Serve deployment.

The judged serve configuration (BASELINE.json north star: "Ray Serve's
replica scheduler runs continuous-batched LLM inference on TPU";
reference analog: serve LLM workloads under ray: release/serve_tests/ and
the vLLM-on-Serve pattern — rebuilt TPU-first rather than ported).

TPU-native shape (SURVEY §7 "Serve continuous batching on TPU"):
  - ONE jitted decode program over a fixed [max_batch] slot array —
    sequences join/leave slots between steps; shapes never change, so XLA
    compiles exactly one decode program (plus one prefill program per
    prompt-length bucket).
  - KV cache is a donated jit argument: decode updates alias in place
    (no per-step cache copy in HBM).
  - Prompt lengths are bucketed to powers of two; padding rows produce
    garbage K/V that the decode mask never admits (llama.prefill).
  - Sampling (greedy / temperature) happens on device; only the [B]
    next-token vector crosses to the host per step.

The engine loop runs on one thread inside the replica actor; requests
arrive via a thread-safe queue and resolve concurrent.futures.Futures,
so the Serve router's async path and the engine's step loop compose.
"""
from __future__ import annotations

import concurrent.futures
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np


def _buckets_for(max_len: int, smallest: int = 32) -> list[int]:
    out, b = [], smallest
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return out


@dataclass
class _Request:
    prompt: list[int]
    max_new_tokens: int
    temperature: float
    eos_id: int | None
    future: concurrent.futures.Future
    submitted_at: float = field(default_factory=time.perf_counter)
    first_token_at: float | None = None
    tokens: list[int] = field(default_factory=list)
    slot: int = -1
    # Optional thread-safe sink for token streaming: every decoded token
    # is pushed as produced; None marks end-of-stream.
    token_queue: Any = None
    # KV pages owned by this request (paged engine); freed at finish.
    pages: list[int] = field(default_factory=list)

    def emit(self, tok: int | None) -> None:
        if self.token_queue is not None:
            self.token_queue.put(tok)


class LLMEngine:
    """Continuous-batching decode engine over llama-family params."""

    def __init__(self, cfg, params=None, *, max_batch: int = 8,
                 max_len: int | None = None, seed: int = 0,
                 steps_per_sync: int = 8, paged: bool = True,
                 page_size: int = 512, kv_pages: int | None = None):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import llama

        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len or cfg.max_seq
        # Decode steps per host round-trip.  Device→host sync latency is
        # the TPU serving bottleneck (through a tunnel it can be >100ms);
        # scanning K steps inside ONE compiled program amortizes it — the
        # multi-step scheduling discipline of TPU LLM servers.  EOS /
        # admission are checked every K tokens; overshoot is trimmed.
        self.steps_per_sync = max(1, steps_per_sync)
        self.params = params if params is not None else llama.init_params(
            jax.random.PRNGKey(seed), cfg)
        self.paged = paged
        if paged:
            # Shared page pool (ops/paged_attention.py): HBM holds the
            # page budget, NOT max_len x slots — max_len can be 32k+
            # while the pool is sized to the expected live footprint.
            # Page 0 is the trash page (idle slots point at it).
            self.page = page_size
            self._maxp = -(-self.max_len // page_size)
            if kv_pages is None:
                kv_pages = 1 + max_batch * (
                    -(-min(self.max_len, 4096) // page_size))
            self.n_pages = kv_pages
            self.cache = llama.init_paged_kv_cache(cfg, max_batch,
                                                   kv_pages, page_size)
            self._free_pages = list(range(1, kv_pages))
            self._table = np.zeros((max_batch, self._maxp), np.int32)
        else:
            # Dense per-layer cache leaves: the stacked [L, ...] cache
            # rode a lax.scan as xs/ys, which XLA cannot alias — every
            # decode step copied the whole cache.
            self.cache = llama.init_kv_cache_leaves(cfg, max_batch,
                                                    self.max_len)
        self._buckets = _buckets_for(self.max_len)
        # Prefill sub-wave cap: a full-width wave serializes the whole
        # burst's forward in front of EVERY first-token fetch (64x128
        # prefill ≈ 40ms compute on a v5e); <=32-wide chunks let the
        # first chunk's tokens reach the host while later chunks are
        # still computing (the fetches overlap via copy_to_host_async).
        self._chunk = min(16, max_batch)
        self._width_buckets = sorted({w for w in (1, 8, self._chunk)
                                      if w <= max_batch})
        self._rng = jax.random.PRNGKey(seed + 1)

        # One compiled K-step decode program; cache donated (in-place).
        def _sample(logits, temps, key):
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = jax.random.categorical(
                key, logits / jnp.maximum(temps, 1e-6)[:, None]
            ).astype(jnp.int32)
            return jnp.where(temps > 0, sampled, greedy)

        def _decode_k_dense(params, cache, tokens, temps, rng, table):
            def step(carry, key):
                cache, toks = carry
                logits, cache = llama.decode_step_unrolled(
                    params, cache, toks, cfg)
                nxt = _sample(logits, temps, key)
                return (cache, nxt), nxt

            keys = jax.random.split(rng, self.steps_per_sync)
            (cache, last), seq = jax.lax.scan(step, (cache, tokens), keys)
            return seq, last, cache   # seq [K, B]

        def _decode_k_paged(params, cache, tokens, temps, rng, table):
            """Pages stay OUT of the scan carry (read-only during the
            block; a carried write would copy the whole pool every
            step); new rows ride a small dense tail, merged into the
            pages once at block end (ops/paged_attention.py)."""
            from ray_tpu.ops.paged_attention import merge_tail_pages

            K = self.steps_per_sync
            ts = cache["pos"]
            pages = {"k": cache["k"], "v": cache["v"]}
            tshape = (max_batch, cfg.n_kv_heads, K, cfg.head_dim)
            tails = {"k": [jnp.zeros(tshape, cfg.dtype)
                           for _ in range(cfg.n_layers)],
                     "v": [jnp.zeros(tshape, cfg.dtype)
                           for _ in range(cfg.n_layers)]}

            def step(carry, xs):
                tails, pos, toks = carry
                key, j = xs
                logits, tails = llama.decode_step_paged(
                    params, pages, tails, toks, pos, ts, j, table, cfg)
                nxt = _sample(logits, temps, key)
                return (tails, pos + 1, nxt), nxt

            keys = jax.random.split(rng, K)
            (tails, pos, last), seq = jax.lax.scan(
                step, (tails, ts, tokens), (keys, jnp.arange(K)))
            new_k = [merge_tail_pages(pages["k"][li], tails["k"][li],
                                      table, ts, K)
                     for li in range(cfg.n_layers)]
            new_v = [merge_tail_pages(pages["v"][li], tails["v"][li],
                                      table, ts, K)
                     for li in range(cfg.n_layers)]
            return seq, last, {"k": new_k, "v": new_v, "pos": pos}

        self._decode = jax.jit(
            _decode_k_paged if paged else _decode_k_dense,
            donate_argnums=(1,))

        # Wave prefill: ONE compiled program admits a whole wave of
        # requests — computes all their prompt KV and scatter-writes each
        # into its slot.  Per-request prefill calls would each round-trip
        # the (donated) cache through the runtime; one call per wave pays
        # that cost once (the dominant serving overhead on a tunneled
        # chip).  Waves are padded by duplicating the last row (same slot
        # written twice with identical data — harmless), so there is one
        # compile per prompt-length bucket, not per wave size.
        def _prefill_wave(params, cache, tokens, true_lens, slots, temps,
                          rng):
            W = tokens.shape[0]
            hidden, ks, vs = llama.prefill(params, tokens, cfg)

            # Scatter each wave member's prompt KV into its slot with ONE
            # batched indexed write per layer leaf (duplicate padded slots
            # carry identical rows, so scatter order is irrelevant; leaves
            # update in place under donation — see init_kv_cache_leaves).
            P = tokens.shape[1]
            k = [cache["k"][li].at[slots, :P].set(ks[li])
                 for li in range(cfg.n_layers)]
            v = [cache["v"][li].at[slots, :P].set(vs[li])
                 for li in range(cfg.n_layers)]
            pos = cache["pos"].at[slots].set(true_lens)
            # Project only the W last-position rows through lm_head (the
            # full [W, P, vocab] logits tensor would be GBs at serving
            # shapes).
            last_h = hidden[jnp.arange(W), true_lens - 1]    # [W, dim]
            last = (last_h @ params["lm_head"]).astype(jnp.float32)
            greedy = jnp.argmax(last, axis=-1).astype(jnp.int32)
            # Per-row keys folded from the SLOT index: duplicate padding
            # rows (same slot, same logits, same temp) then draw the SAME
            # sample, so cur-token and recorded token can't diverge under
            # temperature sampling.
            keys = jax.vmap(lambda s: jax.random.fold_in(rng, s))(slots)
            sampled = jax.vmap(
                lambda k_, l_, t_: jax.random.categorical(
                    k_, l_ / jnp.maximum(t_, 1e-6)))(
                        keys, last, temps).astype(jnp.int32)
            nxt = jnp.where(temps > 0, sampled, greedy)
            return nxt, {"k": k, "v": v, "pos": pos}

        self._prefill = jax.jit(_prefill_wave, donate_argnums=(1,))

        # Paged prefill is SPLIT into two programs: (A) forward +
        # first-token sample, (B) the KV page scatter.  The first-token
        # fetch depends only on A, so its host round trip (the dominant
        # TTFT term on a tunneled chip) overlaps B's 24-layer page
        # writes AND later chunks' forwards instead of queueing behind
        # them (round-5 serve-TTFT rework; the fused program measured
        # ~50ms slower per wave).
        def _prefill_fwd_only(params, tokens, true_lens, slots, temps,
                              rng):
            W = tokens.shape[0]
            hidden, ks, vs = llama.prefill(params, tokens, cfg)
            last_h = hidden[jnp.arange(W), true_lens - 1]
            last = (last_h @ params["lm_head"]).astype(jnp.float32)
            greedy = jnp.argmax(last, axis=-1).astype(jnp.int32)
            keys = jax.vmap(lambda s: jax.random.fold_in(rng, s))(slots)
            sampled = jax.vmap(
                lambda k_, l_, t_: jax.random.categorical(
                    k_, l_ / jnp.maximum(t_, 1e-6)))(
                        keys, last, temps).astype(jnp.int32)
            nxt = jnp.where(temps > 0, sampled, greedy)
            return nxt, ks, vs

        self._prefill_fwd = jax.jit(_prefill_fwd_only)
        self._scatter_pages = jax.jit(
            lambda cache, ks, vs, page_ids, rows, slots, true_lens:
            llama.scatter_prefill_pages(cache, ks, vs, page_ids, rows,
                                        slots, true_lens),
            donate_argnums=(0,))

        # Slot state.  Current tokens live ON DEVICE between blocks: the
        # decode output feeds the next decode input directly, so the only
        # device→host sync per block is the token-sequence fetch.
        self._slots: list[_Request | None] = [None] * max_batch
        self._cur_dev = jnp.zeros((max_batch,), jnp.int32)
        self._temps = np.zeros((max_batch,), np.float32)
        # Device copy of the page table, refreshed only when admission or
        # completion changed it (dense mode passes a constant dummy).
        self._table_dev = jnp.zeros((1, 1), jnp.int32)
        self._table_dirty = paged
        # FIFO backpressure slot: a request whose pages don't fit yet
        # (re-admitted first, never skipped past).
        self._head_of_line: _Request | None = None
        self._set_slots = jax.jit(
            lambda cur, slots, toks: cur.at[slots].set(toks))
        self._waiting: queue.Queue[_Request] = queue.Queue()
        self._error: BaseException | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        # Submissions currently between submit() entry and the queue
        # put, plus the entry stamp of the newest one: _admit skips its
        # burst-coalescing grace when the queue is empty, nobody is
        # mid-submit, and nothing was submitted after the requests it
        # already holds — a lone request must never linger the grace
        # window ("idle requests never wait"), while a burst still
        # coalesces.
        self._inflight_lock = threading.Lock()
        self._inflight_submits = 0
        self._last_submit_t = 0.0
        self.completed = 0

    # ------------------------------------------------------------- public
    def submit(self, prompt: list[int], max_new_tokens: int = 32,
               temperature: float = 0.0,
               eos_id: int | None = None,
               token_queue: "queue.Queue | None" = None,
               ) -> concurrent.futures.Future:
        """Thread-safe; resolves to {tokens, ttft_s, total_s}.  With
        `token_queue`, every decoded token is ALSO pushed to the queue as
        produced (None = end) — the token-streaming hook."""
        if len(prompt) >= self.max_len:
            raise ValueError(
                f"prompt length {len(prompt)} >= max_len {self.max_len}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len {self.max_len}; "
                "decode past the cache end would corrupt output")
        if self.paged:
            need = -(-(len(prompt) + max_new_tokens) // self.page)
            if need > self.n_pages - 1:
                raise ValueError(
                    f"request needs {need} KV pages but the pool holds "
                    f"{self.n_pages - 1}; raise kv_pages (admission "
                    "would otherwise block forever)")
        if self._error is not None:
            raise RuntimeError(
                "LLM engine is dead after an earlier failure") \
                from self._error
        with self._inflight_lock:
            self._inflight_submits += 1
            self._last_submit_t = time.perf_counter()
        try:
            req = _Request(list(prompt), max_new_tokens, temperature,
                           eos_id, concurrent.futures.Future(),
                           token_queue=token_queue)
            self._waiting.put(req)
            self._wake.set()
        finally:
            with self._inflight_lock:
                self._inflight_submits -= 1
        return req.future

    def generate(self, prompt: list[int], max_new_tokens: int = 32,
                 temperature: float = 0.0,
                 eos_id: int | None = None) -> dict:
        """Blocking convenience wrapper."""
        self.start()
        return self.submit(prompt, max_new_tokens, temperature,
                           eos_id).result()

    def warmup(self, buckets: list[int] | None = None) -> None:
        """Pre-compile the decode program and prefill buckets so the first
        real request doesn't pay XLA compile time in its TTFT (the
        standard TPU-serving warmup discipline).  Warmup prompts are
        capped by the paged pool's capacity — a pool sized below one
        full max_len span (the very configurations paging enables) must
        not make warmup trip its own admission check."""
        cap = self.max_len - 1
        if getattr(self, "page", None):
            cap = min(cap, (self.n_pages - 1) * self.page - 1)
        for b in buckets or self._buckets:
            n = min(b, cap)
            if n >= 1:
                self.generate(list(range(1, n + 1)), max_new_tokens=1)

    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="llm-engine", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    # -------------------------------------------------------------- engine
    def _admit(self) -> None:
        """Prefill a whole wave of waiting requests in ONE device call;
        one batched fetch materializes their first tokens."""
        import jax
        import jax.numpy as jnp

        wave: list[tuple[int, _Request]] = []    # (slot, request)
        grace_deadline = None
        while True:
            free = next((i for i, s in enumerate(self._slots)
                         if s is None), None)
            if free is None:
                break
            if self._head_of_line is not None:
                req, self._head_of_line = self._head_of_line, None
            else:
                try:
                    req = self._waiting.get_nowait()
                except queue.Empty:
                    # Burst coalescing: submissions race admission, and a
                    # wave that launches a beat early strands the rest of
                    # the burst behind a full prefill+sync round (~120ms
                    # of loaded TTFT on a tunneled chip).  Once at least
                    # one request is in hand, linger a few ms so the
                    # whole burst rides ONE wave; idle requests never
                    # wait (no linger on an empty wave).
                    if not wave:
                        break
                    if grace_deadline is None:
                        with self._inflight_lock:
                            busy = self._inflight_submits > 0
                            last_t = self._last_submit_t
                        if not busy and last_t <= max(
                                r.submitted_at for _, r in wave):
                            # Lone request(s): nobody is mid-submit and
                            # nothing arrived after the requests already
                            # in hand — launch NOW instead of lingering
                            # the full grace ("idle requests never
                            # wait"); bursts still coalesce because a
                            # racing submit moves _last_submit_t.
                            break
                        grace_deadline = time.perf_counter() + 0.005
                    rem = grace_deadline - time.perf_counter()
                    if rem <= 0:
                        break
                    try:
                        req = self._waiting.get(timeout=rem)
                    except queue.Empty:
                        break
            if self.paged:
                # Allocate the request's full page span up front (prompt
                # + max_new_tokens) — no mid-decode growth, and the pool
                # is the admission control: FIFO blocks when it's dry
                # (vLLM-style KV backpressure).
                need = -(-(len(req.prompt) + req.max_new_tokens)
                         // self.page)
                if len(self._free_pages) < need:
                    self._head_of_line = req
                    break
                req.pages = [self._free_pages.pop()
                             for _ in range(need)]
                self._table[free, :] = 0
                self._table[free, :need] = req.pages
                self._table_dirty = True
            req.slot = free
            self._slots[free] = req
            self._temps[free] = req.temperature
            wave.append((free, req))
        if not wave:
            return
        # Sub-waves of <=_chunk requests: dispatch every chunk's forward
        # (and, paged, its separate scatter program) back-to-back, THEN
        # fetch first tokens — chunk 1's round trip overlaps chunk 2's
        # compute, so a big burst's p50 TTFT tracks one RTT plus HALF
        # the total prefill instead of all of it.
        pending_waves = []        # (chunk, nxt_device)
        for c0 in range(0, len(wave), self._chunk):
            chunk = wave[c0:c0 + self._chunk]
            W = len(chunk)
            bucket = next(b for b in self._buckets
                          if b >= max(len(r.prompt) for _, r in chunk))
            # Pad by duplicating the last row: the duplicate writes the
            # same slot with the same data, so correctness is
            # unaffected.  Width is BUCKETED (1 / 8 / _chunk), not
            # always max_batch: an idle single request padded to a
            # 64-wide wave paid 64x the prefill FLOPs it needed — the
            # round-3 idle-TTFT regression.  Few widths × few length
            # buckets keeps the compile count small.
            padded_w = next(w for w in self._width_buckets if w >= W)
            tokens = np.zeros((padded_w, bucket), np.int32)
            true_lens = np.ones((padded_w,), np.int32)
            slots = np.zeros((padded_w,), np.int32)
            temps = np.zeros((padded_w,), np.float32)
            for j in range(padded_w):
                slot, req = chunk[min(j, W - 1)]
                tokens[j, :len(req.prompt)] = req.prompt
                true_lens[j] = len(req.prompt)
                slots[j] = slot
                temps[j] = req.temperature
            self._rng, sub = jax.random.split(self._rng)
            slots_dev = jnp.asarray(slots)
            lens_dev = jnp.asarray(true_lens)
            if self.paged:
                cols = np.arange(bucket) // self.page
                page_ids = self._table[slots][:, cols]  # [padded_w, bkt]
                rows = np.tile(
                    np.arange(bucket, dtype=np.int32) % self.page,
                    (padded_w, 1))
                nxt, ks, vs = self._prefill_fwd(
                    self.params, jnp.asarray(tokens), lens_dev,
                    slots_dev, jnp.asarray(temps), sub)
                self.cache = self._scatter_pages(
                    self.cache, ks, vs, jnp.asarray(page_ids),
                    jnp.asarray(rows), slots_dev, lens_dev)
            else:
                nxt, self.cache = self._prefill(
                    self.params, self.cache, jnp.asarray(tokens),
                    lens_dev, slots_dev, jnp.asarray(temps), sub)
            # Duplicate padding rows target the same slot + same token.
            self._cur_dev = self._set_slots(self._cur_dev, slots_dev,
                                            nxt)
            pending_waves.append((chunk, nxt))
        for _, nxt in pending_waves:
            try:
                nxt.copy_to_host_async()
            except AttributeError:
                pass
        for chunk, nxt in pending_waves:
            firsts = np.asarray(nxt)[:len(chunk)]
            now = time.perf_counter()
            for (slot, req), first in zip(chunk, firsts):
                req.first_token_at = now
                req.tokens.append(int(first))
                req.emit(int(first))
                if self._done(req):
                    self._finish(slot)

    def _done(self, req: _Request) -> bool:
        return (len(req.tokens) >= req.max_new_tokens
                or (req.eos_id is not None
                    and req.tokens[-1] == req.eos_id))

    def _finish(self, slot: int) -> None:
        req = self._slots[slot]
        self._slots[slot] = None
        self.completed += 1
        if self.paged and req.pages:
            # The freed slot's future (garbage) decode writes go to the
            # trash page once the zeroed table row reaches the device
            # (next _admit or dirty refresh — both before the pages can
            # be re-issued to a new request).
            self._free_pages.extend(req.pages)
            req.pages = []
            self._table[slot, :] = 0
            self._table_dirty = True
        now = time.perf_counter()
        req.emit(None)
        if not req.future.done():
            req.future.set_result({
                "tokens": req.tokens,
                "ttft_s": (req.first_token_at or now) - req.submitted_at,
                "total_s": now - req.submitted_at,
            })

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001
            # Fail every in-flight and waiting request: a silent thread
            # death would hang their futures forever, and the donated
            # cache is invalid after a failed call anyway.
            self._error = e
            if self._head_of_line is not None:
                req, self._head_of_line = self._head_of_line, None
                req.emit(None)
                if not req.future.done():
                    req.future.set_exception(e)
            for i, req in enumerate(self._slots):
                if req is not None:
                    req.emit(None)
                    if not req.future.done():
                        req.future.set_exception(e)
                self._slots[i] = None
            while True:
                try:
                    req = self._waiting.get_nowait()
                except queue.Empty:
                    break
                req.emit(None)
                if not req.future.done():
                    req.future.set_exception(e)
            self._stop.set()
            raise

    def _loop_inner(self) -> None:
        import jax
        import jax.numpy as jnp

        while not self._stop.is_set():
            self._admit()
            active = [i for i, s in enumerate(self._slots)
                      if s is not None]
            if not active:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            self._rng, sub = jax.random.split(self._rng)
            if self._table_dirty:
                self._table_dev = jnp.asarray(self._table) if self.paged \
                    else jnp.zeros((1, 1), jnp.int32)
                self._table_dirty = False
            seq, last, self.cache = self._decode(
                self.params, self.cache, self._cur_dev,
                jnp.asarray(self._temps), sub, self._table_dev)
            self._cur_dev = last                # stays on device
            seq = np.asarray(seq)               # the ONE sync per block
            for i in active:
                req = self._slots[i]
                for tok in seq[:, i]:
                    req.tokens.append(int(tok))
                    req.emit(int(tok))
                    if self._done(req):
                        # Trim K-step overshoot past EOS/max_new_tokens.
                        self._finish(i)
                        break

    def stats(self) -> dict:
        return {"completed": self.completed,
                "active": sum(s is not None for s in self._slots),
                "waiting": self._waiting.qsize(),
                "max_batch": self.max_batch,
                "max_len": self.max_len}


class LLMServer:
    """Serve deployment body: one engine per replica.

    serve.deployment(LLMServer).options(...) — requests carry token-id
    prompts; a tokenizer front can be composed as another deployment.
    """

    def __init__(self, model: str = "debug", *, max_batch: int = 8,
                 max_len: int | None = None, params=None, seed: int = 0,
                 warmup: bool = False, paged: bool = True,
                 page_size: int = 512, kv_pages: int | None = None):
        from ray_tpu.models import llama

        cfg = llama.llama_configs()[model] if isinstance(model, str) \
            else model
        self.engine = LLMEngine(cfg, params, max_batch=max_batch,
                                max_len=max_len, seed=seed, paged=paged,
                                page_size=page_size, kv_pages=kv_pages)
        self.engine.start()
        if warmup:
            self.engine.warmup()

    async def __call__(self, request: dict) -> dict:
        import asyncio

        fut = self.engine.submit(
            request["prompt"],
            max_new_tokens=request.get("max_new_tokens", 32),
            temperature=request.get("temperature", 0.0),
            eos_id=request.get("eos_id"))
        return await asyncio.wrap_future(fut)

    def stream(self, request: dict):
        """Token-streaming generator: yields each token id as the engine
        decodes it.  Consumed via handle.options(stream=True).remote(...)
        or the HTTP proxy's chunked path (x-serve-stream: 1)."""
        if isinstance(request, dict) and "prompt" not in request:
            request = request.get("body") or request
        q: queue.Queue = queue.Queue()
        fut = self.engine.submit(
            request["prompt"],
            max_new_tokens=request.get("max_new_tokens", 32),
            temperature=request.get("temperature", 0.0),
            eos_id=request.get("eos_id"),
            token_queue=q)
        while True:
            tok = q.get()
            if tok is None:
                break
            yield tok
        # The None sentinel is emitted just BEFORE the future resolves;
        # wait briefly so an engine failure can't silently truncate the
        # stream as a clean-looking completion.
        try:
            exc = fut.exception(timeout=5.0)
        except concurrent.futures.TimeoutError:
            exc = None
        if exc is not None:
            raise exc

    def stats(self) -> dict:
        return self.engine.stats()

    def __del__(self):
        try:
            self.engine.stop()
        except Exception:  # noqa: BLE001
            pass
