"""Continuous-batched LLM inference engine + Serve deployment.

The judged serve configuration (BASELINE.json north star: "Ray Serve's
replica scheduler runs continuous-batched LLM inference on TPU";
reference analog: serve LLM workloads under ray: release/serve_tests/ and
the vLLM-on-Serve pattern — rebuilt TPU-first rather than ported).

TPU-native shape (SURVEY §7 "Serve continuous batching on TPU"):
  - ONE jitted decode program over a fixed [max_batch] slot array —
    sequences join/leave slots between steps; shapes never change, so XLA
    compiles exactly one decode program (plus one prefill program per
    prompt-length bucket).
  - KV cache is a donated jit argument: decode updates alias in place
    (no per-step cache copy in HBM).
  - Prompt lengths are bucketed to powers of two; padding rows produce
    garbage K/V that the decode mask never admits (llama.prefill).
  - Sampling (greedy / temperature) happens on device; only the [B]
    next-token vector crosses to the host per step.

Paged KV memory is managed by serve/kv_blocks.py (refcounted blocks,
radix prefix cache, COW) — this file owns the SCHEDULER on top of it:
  - admission matches each prompt's longest cached prefix and prefills
    only the suffix (`prefill_from`);
  - blocks are allocated lazily, one decode window ahead; when the pool
    runs dry the NEWEST request is preempted (blocks committed to the
    prefix cache + released, request re-queued for recompute);
  - sampling keys are per-request (fold_in(engine key, request seed,
    token index)), so a preempted-and-recomputed request draws the same
    tokens it would have drawn uninterrupted — preemption is
    deterministic under seeded sampling, hence testable.
Kill switches: RAY_TPU_PREFIX_CACHE=0 disables prefix matching,
RAY_TPU_KV_PREEMPT=0 restores full-span up-front allocation with FIFO
head-of-line blocking (the pre-block-manager admission semantics).

The engine loop runs on one thread inside the replica actor; requests
arrive via a thread-safe queue and resolve concurrent.futures.Futures,
so the Serve router's async path and the engine's step loop compose.
"""
from __future__ import annotations

import collections
import concurrent.futures
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ray_tpu import memledger
from ray_tpu import tracing
from ray_tpu.exceptions import AdapterLoadError
from ray_tpu.serve import slo
from ray_tpu.serve.kv_blocks import BlockManager


def _buckets_for(max_len: int, smallest: int = 32) -> list[int]:
    out, b = [], smallest
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return out


from ray_tpu.serve.kv_router import env_on as _env_on


def _check_pool_role(role: str, decode_deployment) -> None:
    """The pool-role combination rules, shared by LLMServer.__init__
    and reconfigure (the declarative schema enforces the same rules at
    config time — ENGINE_ROLES is its source of truth)."""
    from ray_tpu.serve.schema import ENGINE_ROLES

    if role not in ENGINE_ROLES:
        raise ValueError(
            f"engine role must be one of {list(ENGINE_ROLES)}, "
            f"got {role!r}")
    if role == "prefill" and decode_deployment is None:
        raise ValueError(
            "role='prefill' requires decode_deployment (the decode "
            "pool this replica ships KV to) — a prefill pool with no "
            "decode pool cannot serve")
    if role != "prefill" and decode_deployment is not None:
        raise ValueError(
            f"decode_deployment only applies to role='prefill' (got "
            f"role={role!r}) — a dangling decode target would "
            "silently serve unified")


def _pow2(n: int) -> int:
    """Smallest power of two >= n: the shared width-bucketing rule of
    the COW / import / export padding paths (one copy — the compile
    count and pad waste must never diverge between them)."""
    m = 1
    while m < n:
        m *= 2
    return m


_METRICS = None
_METRICS_LOCK = threading.Lock()

# Latency-histogram bucket upper bounds in ms: sub-ms router picks
# through tunnel-RTT-dominated prefills (~120ms+) up to pathological
# multi-second p99s the flight recorder exists to attribute.
_MS_BUCKETS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
               1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


def _engine_metrics():
    """Process-wide serve-LLM metrics (utils.metrics registry → flushed
    to the controller KV → dashboard /metrics Prometheus endpoint).
    Tagged per engine so replicas don't clobber each other."""
    global _METRICS
    with _METRICS_LOCK:
        if _METRICS is None:
            from ray_tpu.utils import metrics as um

            tk = ("engine",)
            _METRICS = {
                "prefill_tokens": um.get_or_create(
                    um.Counter, "serve_llm_prefill_tokens",
                    "Prompt tokens actually prefilled on device", tk),
                "prefix_hit_tokens": um.get_or_create(
                    um.Counter, "serve_llm_prefix_hit_tokens",
                    "Prompt tokens served from the KV prefix cache", tk),
                "decode_tokens": um.get_or_create(
                    um.Counter, "serve_llm_decode_tokens",
                    "Tokens decoded", tk),
                "preemptions": um.get_or_create(
                    um.Counter, "serve_llm_preemptions",
                    "Requests preempted for KV blocks", tk),
                "evictions": um.get_or_create(
                    um.Counter, "serve_llm_kv_evictions",
                    "Cached KV blocks LRU-evicted", tk),
                "completed": um.get_or_create(
                    um.Counter, "serve_llm_requests_completed",
                    "Requests completed", tk),
                "occupancy": um.get_or_create(
                    um.Gauge, "serve_llm_batch_occupancy",
                    "Active slots / max_batch", tk),
                "queue_depth": um.get_or_create(
                    um.Gauge, "serve_llm_queue_depth",
                    "Requests waiting for a batch slot (the telemetry "
                    "timeline's engine-queue series)", tk),
                "free_blocks": um.get_or_create(
                    um.Gauge, "serve_llm_kv_free_blocks",
                    "Free KV blocks in the pool", tk),
                "hit_rate": um.get_or_create(
                    um.Gauge, "serve_llm_prefix_hit_rate",
                    "Prefix-cache hit tokens / prompt tokens", tk),
                "weight_version": um.get_or_create(
                    um.Gauge, "serve_llm_weight_version",
                    "Policy weight version currently decoding (online "
                    "RLHF live weight sync)", tk),
                "weight_updates": um.get_or_create(
                    um.Counter, "serve_llm_weight_updates",
                    "Live weight swaps applied between decode syncs", tk),
                # Request-latency histograms (scraped as proper
                # Prometheus histogram families — _bucket/_sum/_count —
                # by the dashboard /metrics exposition).
                "ttft": um.get_or_create(
                    um.Histogram, "serve_request_ttft_ms",
                    "Time to first token per request (ms)", tk,
                    boundaries=_MS_BUCKETS),
                "tpot": um.get_or_create(
                    um.Histogram, "serve_request_tpot_ms",
                    "Time per output token after the first (ms)", tk,
                    boundaries=_MS_BUCKETS),
                "stage": um.get_or_create(
                    um.Histogram, "serve_request_stage_ms",
                    "Per-request stage latency breakdown "
                    "(queue/prefill/decode, ms)", ("engine", "stage"),
                    boundaries=_MS_BUCKETS),
            }
    return _METRICS


@dataclass
class _Request:
    prompt: list[int]
    max_new_tokens: int
    temperature: float
    eos_id: int | None
    future: concurrent.futures.Future
    submitted_at: float = field(default_factory=time.perf_counter)
    first_token_at: float | None = None
    tokens: list[int] = field(default_factory=list)
    slot: int = -1
    # Optional thread-safe sink for token streaming: every decoded token
    # is pushed as produced; None marks end-of-stream.
    token_queue: Any = None
    # KV blocks owned by this request, in table order (block i covers
    # positions [i*page, (i+1)*page)); released at finish/preempt.
    pages: list[int] = field(default_factory=list)
    # Per-request sampling identity: token at generation index g is
    # drawn from fold_in(fold_in(engine_key, sample_seed), g) — timing,
    # batching and preemption cannot change a request's sample stream.
    sample_seed: int = 0
    # First prompt position this admission actually prefills (everything
    # below it came from the prefix cache; 0 = full prefill).
    prefill_from: int = 0
    # False for warmup traffic: never match or populate the prefix
    # cache (warmup must compile the full-prefill bucket programs).
    cache_ok: bool = True
    preempted: int = 0
    # Prefill-pool mode: finish after the first sampled token and
    # attach the request's KV pages (device → host) to the result so
    # the server can migrate them to a decode replica (kv_export).
    prefill_only: bool = False
    # Migrated-KV admission (kv_import): [2, L, n, kvh, page, hd] host
    # array scattered into freshly-allocated pool pages at admission
    # instead of running prefill.  Cleared right after the scatter —
    # this may be a pinned arena view and must not outlive its use.
    import_kv: Any = None
    import_len: int = 0          # valid KV positions in import_kv
    # Prefix-cache generation at admission: a live weight swap bumps
    # the engine's generation and flushes the radix tree; a request
    # admitted under an older generation must NOT commit its blocks
    # (its KV was computed under the old policy).
    cache_gen: int = 0
    # Flight-recorder context captured at submission ((trace_id,
    # span_id) or None — the engine loop replays it when emitting this
    # request's queue/prefill/decode-window spans) plus the wall-clock
    # stamps those spans need (submitted_at/first_token_at are
    # perf_counter, a different basis).
    trace: Any = None
    t0_wall: float = field(default_factory=time.time)
    admitted_at: float = 0.0       # perf_counter at slot assignment
    admitted_wall: float = 0.0
    # Multi-LoRA identity (serve/lora.py): the adapter this request
    # decodes under (None = base model), resolved at ADMISSION to a
    # device bank slot (0 = the all-zeros base row) plus the KV salt
    # keying its radix/prefix-store entries per (adapter, version).
    model_id: str | None = None
    lora_slot: int = 0
    salt: int = 0

    def emit(self, tok: int | None) -> None:
        if self.token_queue is not None:
            self.token_queue.put(tok)


class LLMEngine:
    """Continuous-batching decode engine over llama-family params."""

    def __init__(self, cfg, params=None, *, max_batch: int = 8,
                 max_len: int | None = None, seed: int = 0,
                 steps_per_sync: int = 8, paged: bool = True,
                 page_size: int = 512, kv_pages: int | None = None,
                 prefix_cache: bool | None = None,
                 kv_preempt: bool | None = None,
                 lora_slots: int = 0, lora_rank: int = 0,
                 lora_targets: tuple | None = None,
                 name: str = "llm"):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import llama

        self.cfg = cfg
        self.name = name
        self.max_batch = max_batch
        self.max_len = max_len or cfg.max_seq
        # Decode steps per host round-trip.  Device→host sync latency is
        # the TPU serving bottleneck (through a tunnel it can be >100ms);
        # scanning K steps inside ONE compiled program amortizes it — the
        # multi-step scheduling discipline of TPU LLM servers.  EOS /
        # admission are checked every K tokens; overshoot is trimmed.
        self.steps_per_sync = max(1, steps_per_sync)
        self.params = params if params is not None else llama.init_params(
            jax.random.PRNGKey(seed), cfg)
        self.paged = paged
        self._prefix_cache = paged and (
            prefix_cache if prefix_cache is not None
            else _env_on("RAY_TPU_PREFIX_CACHE"))
        self._preempt_on = paged and (
            kv_preempt if kv_preempt is not None
            else _env_on("RAY_TPU_KV_PREEMPT"))
        if paged:
            # Shared page pool (ops/paged_attention.py): HBM holds the
            # page budget, NOT max_len x slots — max_len can be 32k+
            # while the pool is sized to the expected live footprint.
            # Page 0 is the trash page (idle slots point at it).
            self.page = page_size
            self._maxp = -(-self.max_len // page_size)
            if kv_pages is None:
                kv_pages = 1 + max_batch * (
                    -(-min(self.max_len, 4096) // page_size))
            self.n_pages = kv_pages
            self.cache = llama.init_paged_kv_cache(cfg, max_batch,
                                                   kv_pages, page_size)
            # Host-side accounting: refcounted blocks + radix prefix
            # index over pool ids 1..n_pages-1 (serve/kv_blocks.py).
            self._mgr = BlockManager(kv_pages - 1, page_size,
                                     prefix_cache=self._prefix_cache)
            self._table = np.zeros((max_batch, self._maxp), np.int32)
        else:
            # Dense per-layer cache leaves: the stacked [L, ...] cache
            # rode a lax.scan as xs/ys, which XLA cannot alias — every
            # decode step copied the whole cache.
            self.cache = llama.init_kv_cache_leaves(cfg, max_batch,
                                                    self.max_len)
            self._mgr = None
        self._buckets = _buckets_for(self.max_len)
        # Prefill sub-wave cap: a full-width wave serializes the whole
        # burst's forward in front of EVERY first-token fetch (64x128
        # prefill ≈ 40ms compute on a v5e); <=32-wide chunks let the
        # first chunk's tokens reach the host while later chunks are
        # still computing (the fetches overlap via copy_to_host_async).
        self._chunk = min(16, max_batch)
        self._width_buckets = sorted({w for w in (1, 8, self._chunk)
                                      if w <= max_batch})
        # Per-request sampling base key (see _Request.sample_seed).
        self._base_key = jax.random.PRNGKey(seed + 1)

        # Multi-LoRA device banks (serve/lora.py): per-target stacked
        # [L, n_slots, din, r] / [L, n_slots, r, dout] arrays that a
        # per-request int32 slot index gathers inside the ONE jitted
        # decode/prefill program (models/llama._lora_proj) — adapters
        # swap by bank-row writes, never by retrace.  Slot 0 is the
        # all-zeros base row (y + 0.0 == y exactly), so base and
        # adapter requests mix freely within a batch.
        self.lora_slots = max(0, int(lora_slots))
        self.lora_rank = int(lora_rank) if self.lora_slots else 0
        if self.lora_slots:
            if not paged:
                raise ValueError(
                    "lora_slots > 0 requires a paged engine (adapter "
                    "KV identity is radix/page-granular)")
            if self.lora_rank < 1:
                raise ValueError(
                    "lora_slots > 0 requires lora_rank >= 1 (bank "
                    "shapes are static — the XLA invariants)")
            dims = llama.lora_target_dims(cfg)
            tgts = tuple(lora_targets or llama.LORA_TARGETS)
            bad = [t for t in tgts if t not in dims]
            if bad:
                raise ValueError(
                    f"unknown lora targets {bad}; valid: {sorted(dims)}")
            ns = self.lora_slots + 1
            self._lora_banks = {
                t: {"a": jnp.zeros((cfg.n_layers, ns, dims[t][0],
                                    self.lora_rank), cfg.dtype),
                    "b": jnp.zeros((cfg.n_layers, ns, self.lora_rank,
                                    dims[t][1]), cfg.dtype)}
                for t in tgts}
            self._lora_free = list(range(1, ns))
        else:
            self._lora_banks = None
            self._lora_free = []
        # Slot-resolution state: model_id -> bank slot + metadata, the
        # per-lane slot indices the decode program gathers with, and
        # the ONE lock covering evict-choose + map-update AND the
        # admission-time resolution.  load_adapter runs on CALLER
        # threads; the banks dict swaps atomically and jax arrays are
        # immutable, so in-flight dispatches keep the tree they
        # captured.
        self._lora_lock = threading.Lock()
        self._lora_map: dict[str, int] = {}
        self._lora_meta: dict[str, dict] = {}
        self._adapters = np.zeros((max_batch,), np.int32)
        self.adapter_loads = 0
        self.adapter_evictions = 0

        def _sample_rows(logits, temps, keys):
            """Per-row sampling: each row draws from ITS OWN key — the
            sample stream belongs to the request, not to the batch."""
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = jax.vmap(
                lambda k_, l_, t_: jax.random.categorical(
                    k_, l_ / jnp.maximum(t_, 1e-6)))(
                        keys, logits, temps).astype(jnp.int32)
            return jnp.where(temps > 0, sampled, greedy)

        def _first_token(params, last_h, temps, seeds, starts):
            last = (last_h @ params["lm_head"]).astype(jnp.float32)
            keys = jax.vmap(
                lambda s, t: jax.random.fold_in(
                    jax.random.fold_in(self._base_key, s), t))(seeds,
                                                               starts)
            return _sample_rows(last, temps, keys)

        # Compiled K-step decode programs, one per sync-window size.
        # K is baked into the scan at trace time (jit caches on
        # argument shapes, never on closure attributes), so the
        # overload ladder's "shrink the sync window" knob needs a
        # factory: each window size compiles once and stays cached.
        def _make_decode(K):
            def _decode_k_dense(params, cache, tokens, temps, table,
                                seeds, starts, lora):
                lane_keys = jax.vmap(
                    lambda s: jax.random.fold_in(self._base_key,
                                                 s))(seeds)

                def step(carry, j):
                    cache, toks = carry
                    logits, cache = llama.decode_step_unrolled(
                        params, cache, toks, cfg)
                    keys = jax.vmap(jax.random.fold_in)(lane_keys,
                                                        starts + j)
                    nxt = _sample_rows(logits, temps, keys)
                    return (cache, nxt), nxt

                (cache, last), seq = jax.lax.scan(
                    step, (cache, tokens), jnp.arange(K))
                return seq, last, cache   # seq [K, B]

            def _decode_k_paged(params, cache, tokens, temps, table,
                                seeds, starts, lora):
                """Pages stay OUT of the scan carry (read-only during
                the block; a carried write would copy the whole pool
                every step); new rows ride a small dense tail, merged
                into the pages once at block end
                (ops/paged_attention.py)."""
                from ray_tpu.ops.paged_attention import merge_tail_pages

                ts = cache["pos"]
                pages = {"k": cache["k"], "v": cache["v"]}
                tshape = (max_batch, cfg.n_kv_heads, K, cfg.head_dim)
                tails = {"k": [jnp.zeros(tshape, cfg.dtype)
                               for _ in range(cfg.n_layers)],
                         "v": [jnp.zeros(tshape, cfg.dtype)
                               for _ in range(cfg.n_layers)]}
                lane_keys = jax.vmap(
                    lambda s: jax.random.fold_in(self._base_key,
                                                 s))(seeds)

                def step(carry, j):
                    tails, pos, toks = carry
                    logits, tails = llama.decode_step_paged(
                        params, pages, tails, toks, pos, ts, j, table,
                        cfg, lora)
                    keys = jax.vmap(jax.random.fold_in)(lane_keys,
                                                        starts + j)
                    nxt = _sample_rows(logits, temps, keys)
                    return (tails, pos + 1, nxt), nxt

                (tails, pos, last), seq = jax.lax.scan(
                    step, (tails, ts, tokens), jnp.arange(K))
                new_k = [merge_tail_pages(pages["k"][li],
                                          tails["k"][li], table, ts, K)
                         for li in range(cfg.n_layers)]
                new_v = [merge_tail_pages(pages["v"][li],
                                          tails["v"][li], table, ts, K)
                         for li in range(cfg.n_layers)]
                return seq, last, {"k": new_k, "v": new_v, "pos": pos}

            return jax.jit(_decode_k_paged if paged else _decode_k_dense,
                           donate_argnums=(1,))

        self._make_decode = _make_decode
        self._decode_fns = {self.steps_per_sync:
                            _make_decode(self.steps_per_sync)}
        # Live sync-window size: the loop decodes this many steps per
        # host round trip.  Shrunk under sustained overload (smaller
        # windows = more admission points = bounded queued-TTFT at a
        # throughput cost), restored on recovery — see set_sync_window.
        self._k_live = self.steps_per_sync
        self.sync_window_shrinks = 0

        # Wave prefill: ONE compiled program admits a whole wave of
        # requests — computes all their prompt KV and scatter-writes each
        # into its slot.  Per-request prefill calls would each round-trip
        # the (donated) cache through the runtime; one call per wave pays
        # that cost once (the dominant serving overhead on a tunneled
        # chip).  Waves are padded by duplicating the last row (same slot
        # written twice with identical data — harmless), so there is one
        # compile per prompt-length bucket, not per wave size.
        def _prefill_wave(params, cache, tokens, true_lens, slots, temps,
                          seeds, starts):
            W = tokens.shape[0]
            hidden, ks, vs = llama.prefill(params, tokens, cfg)

            # Scatter each wave member's prompt KV into its slot with ONE
            # batched indexed write per layer leaf (duplicate padded slots
            # carry identical rows, so scatter order is irrelevant; leaves
            # update in place under donation — see init_kv_cache_leaves).
            P = tokens.shape[1]
            k = [cache["k"][li].at[slots, :P].set(ks[li])
                 for li in range(cfg.n_layers)]
            v = [cache["v"][li].at[slots, :P].set(vs[li])
                 for li in range(cfg.n_layers)]
            pos = cache["pos"].at[slots].set(true_lens)
            # Project only the W last-position rows through lm_head (the
            # full [W, P, vocab] logits tensor would be GBs at serving
            # shapes).  Duplicate padding rows carry the same
            # (seed, start), so they draw the SAME sample — cur-token
            # and recorded token can't diverge under temperature.
            last_h = hidden[jnp.arange(W), true_lens - 1]    # [W, dim]
            nxt = _first_token(params, last_h, temps, seeds, starts)
            return nxt, {"k": k, "v": v, "pos": pos}

        self._prefill = jax.jit(_prefill_wave, donate_argnums=(1,))

        # Paged prefill is SPLIT into two programs: (A) forward +
        # first-token sample, (B) the KV page scatter.  The first-token
        # fetch depends only on A, so its host round trip (the dominant
        # TTFT term on a tunneled chip) overlaps B's 24-layer page
        # writes AND later chunks' forwards instead of queueing behind
        # them (round-5 serve-TTFT rework; the fused program measured
        # ~50ms slower per wave).
        def _prefill_fwd_only(params, tokens, true_lens, slots, temps,
                              seeds, starts, lora):
            W = tokens.shape[0]
            hidden, ks, vs = llama.prefill(params, tokens, cfg, lora)
            last_h = hidden[jnp.arange(W), true_lens - 1]
            nxt = _first_token(params, last_h, temps, seeds, starts)
            return nxt, ks, vs

        self._prefill_fwd = jax.jit(_prefill_fwd_only)

        # Prefix-cache suffix prefill (program A'): forward ONLY the
        # tokens the radix cache didn't cover, attending the cached
        # prefix through the page pool (llama.prefill_with_prefix).
        # Same split as above: the scatter rides program B.
        def _prefill_suffix_fwd(params, kp, vp, tokens, pos0, prefix_t,
                                last_idx, temps, seeds, starts, lora):
            W = tokens.shape[0]
            hidden, ks, vs = llama.prefill_with_prefix(
                params, tokens, pos0, cfg, kp, vp, prefix_t, lora)
            last_h = hidden[jnp.arange(W), last_idx]
            nxt = _first_token(params, last_h, temps, seeds, starts)
            return nxt, ks, vs

        self._prefill_suffix = jax.jit(_prefill_suffix_fwd)

        self._scatter_pages = jax.jit(
            lambda cache, ks, vs, page_ids, rows, slots, true_lens:
            llama.scatter_prefill_pages(cache, ks, vs, page_ids, rows,
                                        slots, true_lens),
            donate_argnums=(0,))
        # Suffix scatters start mid-span (prefill_from), so the
        # page-aligned fast paths don't apply — force the coordinate
        # form (see scatter_prefill_pages).
        self._scatter_pages_coord = jax.jit(
            lambda cache, ks, vs, page_ids, rows, slots, true_lens:
            llama.scatter_prefill_pages(cache, ks, vs, page_ids, rows,
                                        slots, true_lens, aligned=False),
            donate_argnums=(0,))
        # KV migration surface (prefill/decode disaggregation).  Export
        # gathers a request's pages into ONE stacked [2, L, n, kvh,
        # page, hd] array (a single host fetch, a single object-plane
        # put); import scatters such an array into freshly-allocated
        # pages and seeds the slot's pos/current-token — together they
        # let a decode engine resume exactly where a prefill engine
        # stopped.  Widths are padded to powers of two (pad ids target
        # the trash page 0, whose content is garbage by contract) so
        # the compile count stays logarithmic.
        def _gather_kv_fn(ks, vs, ids):
            return jnp.stack([jnp.stack([k[ids] for k in ks]),
                              jnp.stack([v[ids] for v in vs])])

        self._gather_kv = jax.jit(_gather_kv_fn)

        def _import_kv_fn(cache, cur, kv, ids, slot, kvlen, tok):
            k = [cache["k"][li].at[ids].set(kv[0, li])
                 for li in range(cfg.n_layers)]
            v = [cache["v"][li].at[ids].set(kv[1, li])
                 for li in range(cfg.n_layers)]
            pos = cache["pos"].at[slot].set(kvlen)
            return ({"k": k, "v": v, "pos": pos},
                    cur.at[slot].set(tok))

        self._import_pages = jax.jit(_import_kv_fn,
                                     donate_argnums=(0, 1))

        # Prefix-store graft: scatter a stored subtree's KV into fresh
        # pool blocks WITHOUT touching any slot (kv_import resumes a
        # request; a graft only re-warms the radix tree — the blocks
        # are committed+released right after, so the next admission
        # prefix-hits them).  Same pow-2 width padding as import.
        def _graft_kv_fn(cache, kv, ids):
            k = [cache["k"][li].at[ids].set(kv[0, li])
                 for li in range(cfg.n_layers)]
            v = [cache["v"][li].at[ids].set(kv[1, li])
                 for li in range(cfg.n_layers)]
            return {"k": k, "v": v, "pos": cache["pos"]}

        self._graft_pages = jax.jit(_graft_kv_fn, donate_argnums=(0,))

        # COW page copy: duplicate shared blocks before a writer touches
        # them.  Pairs are padded with (0, 0) — trash-to-trash is a
        # no-op — so the compile count stays at a few pad widths.
        self._copy_pages = jax.jit(
            lambda cache, src, dst: {
                "k": [l.at[dst].set(l[src]) for l in cache["k"]],
                "v": [l.at[dst].set(l[src]) for l in cache["v"]],
                "pos": cache["pos"]},
            donate_argnums=(0,))

        # Slot state.  Current tokens live ON DEVICE between blocks: the
        # decode output feeds the next decode input directly, so the only
        # device→host sync per block is the token-sequence fetch.
        self._slots: list[_Request | None] = [None] * max_batch
        self._cur_dev = jnp.zeros((max_batch,), jnp.int32)
        self._temps = np.zeros((max_batch,), np.float32)
        self._seeds = np.zeros((max_batch,), np.int32)
        # Device copy of the page table, refreshed only when admission or
        # completion changed it (dense mode passes a constant dummy).
        self._table_dev = jnp.zeros((1, 1), jnp.int32)
        self._table_dirty = paged
        # Admission order: new submissions drain from the thread-safe
        # queue into this deque; preempted requests re-enter at the
        # FRONT (they keep their place — recompute, not starvation).
        # The front request is the head-of-line FIFO barrier when the
        # pool can't cover it yet.
        self._pending: collections.deque[_Request] = collections.deque()
        self._waiting: queue.Queue[_Request] = queue.Queue()
        self._error: BaseException | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        # Submissions currently between submit() entry and the queue
        # put, plus the entry stamp of the newest one: _admit skips its
        # burst-coalescing grace when the queue is empty, nobody is
        # mid-submit, and nothing was submitted after the requests it
        # already holds — a lone request must never linger the grace
        # window ("idle requests never wait"), while a burst still
        # coalesces.
        self._inflight_lock = threading.Lock()
        self._inflight_submits = 0
        self._last_submit_t = 0.0
        self._next_seed = 0
        self.completed = 0
        self.preemptions = 0
        self.kv_exports = 0            # prefill-side page migrations out
        self.kv_imports = 0            # decode-side page migrations in
        # Export side-channel (created lazily by the loop thread on the
        # first prefill_only finish): the device→host fetch of migrated
        # KV runs here so the decode loop never blocks on it.
        self._export_q: queue.Queue | None = None
        self._export_thread: threading.Thread | None = None
        self.prefill_tokens = 0        # tokens actually prefilled
        self.decode_tokens = 0
        # Live weight sync (online RLHF): update_weights() stages a
        # fresh param tree here; the loop swaps it in BETWEEN decode
        # sync windows (never mid-block — the compiled program must see
        # one consistent tree), so decode continues uninterrupted and a
        # generation replica is never drained for a policy update.
        self._weights_lock = threading.Lock()
        self._staged_weights: tuple | None = None   # (version, tree, t)
        self._staged_version = 0
        self.weight_version = 0
        self.weight_updates = 0
        self.weight_syncs_skipped = 0
        self.last_weight_sync_ms = 0.0   # stage -> visible-to-decode
        # Prefix-cache generation: bumped (and the radix tree flushed)
        # at every weight swap — cached KV belongs to the policy that
        # computed it.
        self._cache_gen = 0
        # Tier-2 prefix store (serve/prefix_store.py): the owning
        # server installs a demotion callback via set_prefix_store;
        # the loop then demotes cold radix leaves into sealed arena
        # objects (gather dispatched on the loop, host fetch + publish
        # on the export thread) and applies queued grafts.  All
        # no-ops until a callback is installed.
        self._demote_cb = None
        self._demote_knobs: dict = {}
        self._demote_lock = threading.Lock()
        self._demote_inflight = 0
        self._demote_t = 0.0
        # Leaf hashes the store declined — skipped on rescans so a
        # disabled/full store doesn't re-gather the same leaves every
        # period.  Cleared on weight swaps with the tree flush.
        self._demote_skip: set[int] = set()
        self._graft_q: queue.Queue = queue.Queue()
        self.kv_grafts = 0
        self.graft_tokens = 0
        self.demote_published = 0
        self.demote_failures = 0
        # Recent per-request latency window (exact p99 over raw samples
        # — the controller's SLO loop consumes this via stats() →
        # replica_metrics; the histograms quantize, this doesn't).
        self._slo_window = slo.LatencyWindow()
        self._metrics_last: dict[str, float] = {}
        self._metrics_t = 0.0
        # stats() flushes from replica threads while the loop flushes on
        # its own cadence; the delta bookkeeping must not double-count.
        self._metrics_lock = threading.Lock()

    # ------------------------------------------------------------- public
    def submit(self, prompt: list[int], max_new_tokens: int = 32,
               temperature: float = 0.0,
               eos_id: int | None = None,
               token_queue: "queue.Queue | None" = None,
               _cache_ok: bool = True,
               prefill_only: bool = False,
               model_id: str | None = None,
               ) -> concurrent.futures.Future:
        """Thread-safe; resolves to {tokens, ttft_s, total_s}.  With
        `token_queue`, every decoded token is ALSO pushed to the queue as
        produced (None = end) — the token-streaming hook.  With
        `prefill_only` (paged engines), the result additionally carries
        `kv_export`: the request's KV pages as one host array plus the
        metadata kv_import() needs to resume decoding on ANOTHER engine
        (the prefill half of disaggregated serving).  With `model_id`,
        the request decodes under that LoRA adapter's bank slot (it
        must be resident — load_adapter — by ADMISSION time, or the
        future fails with AdapterLoadError) and its KV cache entries
        key on the adapter's salt."""
        if prefill_only and not self.paged:
            raise ValueError(
                "prefill_only requires a paged engine (KV export is "
                "page-granular)")
        if model_id is not None and self._lora_banks is None:
            raise AdapterLoadError(
                "engine has no adapter slots (set lora_slots)",
                model_id=model_id, deployment=self.name,
                reason="lora_slots=0")
        if len(prompt) >= self.max_len:
            raise ValueError(
                f"prompt length {len(prompt)} >= max_len {self.max_len}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len {self.max_len}; "
                "decode past the cache end would corrupt output")
        if self.paged:
            need = -(-(len(prompt) + max_new_tokens) // self.page)
            if need > self.n_pages - 1:
                raise ValueError(
                    f"request needs {need} KV pages but the pool holds "
                    f"{self.n_pages - 1}; raise kv_pages (admission "
                    "would otherwise block forever)")
        if self._error is not None:
            raise RuntimeError(
                "LLM engine is dead after an earlier failure") \
                from self._error
        with self._inflight_lock:
            self._inflight_submits += 1
            self._last_submit_t = time.perf_counter()
            seed = self._next_seed
            self._next_seed += 1
        try:
            req = _Request(list(prompt), max_new_tokens, temperature,
                           eos_id, concurrent.futures.Future(),
                           token_queue=token_queue, sample_seed=seed,
                           cache_ok=_cache_ok, prefill_only=prefill_only,
                           model_id=model_id)
            if tracing.ENABLED:
                req.trace = tracing.capture()
            self._waiting.put(req)
            self._wake.set()
        finally:
            with self._inflight_lock:
                self._inflight_submits -= 1
        return req.future

    def generate(self, prompt: list[int], max_new_tokens: int = 32,
                 temperature: float = 0.0,
                 eos_id: int | None = None,
                 _cache_ok: bool = True) -> dict:
        """Blocking convenience wrapper."""
        self.start()
        return self.submit(prompt, max_new_tokens, temperature,
                           eos_id, _cache_ok=_cache_ok).result()

    def kv_import(self, prompt: list[int], tokens: list[int], kv,
                  *, kv_len: int, max_new_tokens: int = 32,
                  temperature: float = 0.0, eos_id: int | None = None,
                  sample_seed: int = 0,
                  token_queue: "queue.Queue | None" = None,
                  ) -> concurrent.futures.Future:
        """Resume a request whose prefill ran on ANOTHER engine: `kv` is
        that engine's `kv_export` array ([2, L, n, kvh, page, hd],
        gather_pages-compatible page layout), covering the first
        `kv_len` positions of prompt+tokens.  The pages are scattered
        into freshly-allocated pool blocks at admission; decode then
        continues from tokens[-1] exactly as if prefill had run here.
        With matching engine seeds and the exporter's `sample_seed`,
        the continued sample stream is bit-identical to an uninterrupted
        single-engine run (the migration-parity contract).  The future
        resolves like submit()'s — `tokens` in the result INCLUDES the
        ones passed in."""
        from ray_tpu import failpoints

        if failpoints.ACTIVE:
            failpoints.fire("serve.kv_import")
        if not self.paged:
            raise ValueError("kv_import requires a paged engine")
        if not tokens:
            raise ValueError("kv_import needs at least the first "
                             "generated token")
        if len(tokens) > max_new_tokens:
            # Under-reserving pages for a negative remaining budget
            # would blow up inside the jitted scatter ON THE ENGINE
            # LOOP (killing every tenant) — reject at the API edge like
            # every other misuse.
            raise ValueError(
                f"already have {len(tokens)} generated tokens but "
                f"max_new_tokens is {max_new_tokens}")
        if kv_len != len(prompt) + len(tokens) - 1:
            raise ValueError(
                f"kv_len {kv_len} != prompt+tokens-1 "
                f"({len(prompt) + len(tokens) - 1}): exported KV must "
                "cover every position but the newest token's")
        kv = np.asarray(kv)
        L = self.cfg.n_layers
        n_imp = -(-kv_len // self.page)
        want = (2, L, n_imp, self.cfg.n_kv_heads, self.page,
                self.cfg.head_dim)
        if kv.shape != want:
            raise ValueError(
                f"kv shape {kv.shape} does not match this engine "
                f"(expected {want}: page_size/config mismatch between "
                "prefill and decode pools?)")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len {self.max_len}")
        need = -(-(len(prompt) + max_new_tokens) // self.page)
        if need > self.n_pages - 1:
            raise ValueError(
                f"request needs {need} KV pages but the pool holds "
                f"{self.n_pages - 1}; raise kv_pages")
        if self._error is not None:
            raise RuntimeError(
                "LLM engine is dead after an earlier failure") \
                from self._error
        req = _Request(list(prompt), max_new_tokens, temperature,
                       eos_id, concurrent.futures.Future(),
                       token_queue=token_queue, sample_seed=sample_seed,
                       tokens=list(tokens), import_kv=kv,
                       import_len=kv_len)
        if tracing.ENABLED:
            req.trace = tracing.capture()
        self._waiting.put(req)
        self._wake.set()
        return req.future

    def set_prefix_store(self, publish_cb, *, min_idle: int = 256,
                         period_s: float = 0.25,
                         watermark_frac: float = 0.125,
                         limit: int = 2, max_inflight: int = 2) -> None:
        """Install (or, with None, remove) the tier-2 prefix-store
        demotion hook (serve/prefix_store.py).  `publish_cb(entry)`
        runs on the EXPORT thread with the demoted subtree's host KV
        ({tokens, kv, hashes, depth, page, weight_version}) and returns
        True once tier 2 holds it — only then is the tier-1 leaf
        evicted.  Knobs: a leaf demotes after `min_idle` LRU-clock
        ticks of disuse, or immediately when the free pool falls under
        `watermark_frac` (demote-before-evict: plain eviction would
        destroy KV the cluster could reuse); at most `limit` leaves per
        `period_s` scan and `max_inflight` unfinished demotions."""
        self._demote_cb = publish_cb
        self._demote_knobs = dict(
            min_idle=max(0, int(min_idle)),
            period_s=max(0.01, float(period_s)),
            watermark=int(max(0.0, float(watermark_frac))
                          * (self.n_pages - 1)) if self.paged else 0,
            limit=max(1, int(limit)),
            max_inflight=max(1, int(max_inflight)))
        with self._demote_lock:
            self._demote_skip.clear()

    def kv_graft(self, tokens: list[int], kv, *, kv_len: int,
                 weight_version: int | None = None, salt: int = 0,
                 ) -> concurrent.futures.Future:
        """Graft a stored prefix's KV into this engine's pool: scatter
        `kv` (kv_export page layout, [2, L, n, kvh, page, hd]) into
        freshly-allocated blocks and COMMIT them into the radix tree
        under `tokens` — the next request matching the prefix hits
        tier 1 as if it had been computed here.  Full blocks only
        (kv_len must be a page multiple covering all of `tokens`).
        Applied on the engine loop between decode windows; the future
        resolves to {"grafted": n_blocks} or {"grafted": 0, "reason"}
        when skipped — a `weight_version` mismatch at application time
        NEVER grafts (stale-policy KV must not repollute a flushed
        cache).  `salt` keys the committed radix entry per (adapter,
        version) — see serve/lora.adapter_salt; 0 = base model."""
        import numpy as np

        if not self.paged:
            raise ValueError("kv_graft requires a paged engine")
        if kv_len <= 0 or kv_len % self.page != 0:
            raise ValueError(
                f"kv_len {kv_len} must be a positive multiple of the "
                f"page size {self.page} (the radix tree is "
                "block-granular)")
        if len(tokens) != kv_len:
            raise ValueError(
                f"tokens ({len(tokens)}) must cover exactly kv_len "
                f"({kv_len}) positions")
        kv = np.asarray(kv)
        n = kv_len // self.page
        want = (2, self.cfg.n_layers, n, self.cfg.n_kv_heads,
                self.page, self.cfg.head_dim)
        if kv.shape != want:
            raise ValueError(
                f"kv shape {kv.shape} does not match this engine "
                f"(expected {want}: page_size/config mismatch?)")
        if n > self.n_pages - 1:
            raise ValueError(
                f"graft needs {n} KV pages but the pool holds "
                f"{self.n_pages - 1}")
        if self._error is not None:
            raise RuntimeError(
                "LLM engine is dead after an earlier failure") \
                from self._error
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._graft_q.put((list(tokens), kv, n, weight_version,
                           int(salt), fut))
        self._wake.set()
        return fut

    # ------------------------------------------------------- multi-LoRA
    def _lora_args(self, idx) -> dict | None:
        """The per-call `lora` jit argument: None (the static base-path
        trace) when the engine has no banks, else {"idx": [W] int32
        bank-slot per lane, "banks": the resident stacks}.  Banks are
        jit ARGUMENTS, so a load_adapter bank swap changes data, never
        the compiled program."""
        if self._lora_banks is None:
            return None
        import jax.numpy as jnp

        return {"idx": jnp.asarray(np.asarray(idx, np.int32)),
                "banks": self._lora_banks}

    def load_adapter(self, model_id: str, adapter: dict, *,
                     version: int = 1) -> int:
        """Make an adapter device-resident: validate against THIS
        engine's config, pick a bank slot (free list, else LRU among
        slots no in-flight request decodes with), and scatter the
        [L, din, r]/[L, r, dout] stacks into the slot's bank rows.
        Functional `.at[:, slot].set()` writes and an atomic banks-dict
        swap mean this runs on the CALLER thread while decode continues
        (dispatched programs keep the immutable tree they captured).
        Re-loading a resident (model_id, version) is a no-op; a new
        version overwrites in place — its KV salt differs, so stale
        cached KV goes unreachable rather than corrupt.  Raises a typed
        AdapterLoadError when the weights don't fit this engine or no
        slot can be freed — reject early, never a wedged loop.  The
        `serve.adapter_swap` failpoint fires BEFORE an eviction mutates
        anything."""
        import jax.numpy as jnp

        from ray_tpu import failpoints
        from ray_tpu.models import llama
        from ray_tpu.serve import lora as lora_mod

        if self._lora_banks is None:
            raise AdapterLoadError(
                "engine has no adapter slots (set lora_slots)",
                model_id=model_id, deployment=self.name,
                reason="lora_slots=0")
        targets = (adapter or {}).get("targets") or {}
        dims = llama.lora_target_dims(self.cfg)
        rank = 0
        for t, ab in targets.items():
            if t not in self._lora_banks:
                raise AdapterLoadError(
                    f"adapter targets {t!r} but this engine banks "
                    f"{sorted(self._lora_banks)}", model_id=model_id,
                    deployment=self.name, reason="bad_target")
            a, b = np.asarray(ab["a"]), np.asarray(ab["b"])
            din, dout = dims[t]
            if (a.ndim != 3 or b.ndim != 3
                    or a.shape[0] != self.cfg.n_layers
                    or a.shape[1] != din or b.shape[2] != dout
                    or a.shape[2] != b.shape[1]):
                raise AdapterLoadError(
                    f"adapter target {t!r} shapes {a.shape}/{b.shape} "
                    f"do not fit this engine (want "
                    f"[L={self.cfg.n_layers}, {din}, r] / "
                    f"[L, r, {dout}])", model_id=model_id,
                    deployment=self.name, reason="bad_shape")
            rank = max(rank, a.shape[2])
        if rank < 1:
            raise AdapterLoadError(
                "adapter has no targets", model_id=model_id,
                deployment=self.name, reason="empty")
        if rank > self.lora_rank:
            raise AdapterLoadError(
                f"adapter rank {rank} exceeds the engine's static bank "
                f"rank {self.lora_rank} (lora_rank)",
                model_id=model_id, deployment=self.name,
                reason="rank_overflow")
        with self._lora_lock:
            cur = self._lora_map.get(model_id)
            if cur is not None \
                    and self._lora_meta[model_id]["version"] == version:
                self._lora_meta[model_id]["last_used"] = time.monotonic()
                return cur
            if cur is not None:
                slot = cur                      # re-upload in place
            elif self._lora_free:
                slot = self._lora_free.pop(0)
            else:
                in_use = {int(s) for s in self._adapters if s}
                cands = [(self._lora_meta[mid]["last_used"], mid)
                         for mid, s in self._lora_map.items()
                         if s not in in_use]
                if not cands:
                    raise AdapterLoadError(
                        "every adapter slot has an in-flight request",
                        model_id=model_id, deployment=self.name,
                        reason="no_free_slot")
                if failpoints.ACTIVE:
                    # Pre-mutation: an injected fault here must leave
                    # the resident set exactly as it was.
                    failpoints.fire("serve.adapter_swap")
                _, victim = min(cands)
                slot = self._lora_map.pop(victim)
                del self._lora_meta[victim]
                self.adapter_evictions += 1
                if tracing.ENABLED:
                    tracing.emit(
                        "serve.adapter_swap", time.time(),
                        attrs={"deployment": self.name, "slot": slot,
                               "loaded": model_id, "evicted": victim})
            banks = {}
            for t, bank in self._lora_banks.items():
                ab = targets.get(t)
                if ab is None:
                    # Absent target: zero the slot row (no delta).
                    a = jnp.zeros_like(bank["a"][:, 0])
                    b = jnp.zeros_like(bank["b"][:, 0])
                else:
                    a = jnp.asarray(ab["a"], bank["a"].dtype)
                    b = jnp.asarray(ab["b"], bank["b"].dtype)
                    r = a.shape[2]
                    if r < self.lora_rank:
                        # Zero-pad narrow adapters into the static
                        # bank rank: the padded columns/rows contribute
                        # exactly zero to the delta.
                        a = jnp.concatenate(
                            [a, jnp.zeros(a.shape[:2]
                                          + (self.lora_rank - r,),
                                          a.dtype)], axis=2)
                        b = jnp.concatenate(
                            [b, jnp.zeros((b.shape[0],
                                           self.lora_rank - r)
                                          + b.shape[2:], b.dtype)],
                            axis=1)
                banks[t] = {"a": bank["a"].at[:, slot].set(a),
                            "b": bank["b"].at[:, slot].set(b)}
            self._lora_banks = banks
            self._lora_map[model_id] = slot
            self._lora_meta[model_id] = {
                "version": int(version),
                "salt": lora_mod.adapter_salt(model_id, version),
                "rank": rank, "last_used": time.monotonic()}
            self.adapter_loads += 1
            return slot

    def adapter_resident(self, model_id: str,
                         version: int | None = None) -> bool:
        """Residency probe (the server's per-request fast path): True
        when the adapter — at `version`, if given — holds a bank
        slot."""
        with self._lora_lock:
            meta = self._lora_meta.get(model_id)
            return (meta is not None
                    and (version is None or meta["version"] == version))

    def adapter_touch(self, model_id: str) -> None:
        """Stamp an adapter's LRU clock (the server's resident fast
        path calls this per request): eviction must rank by actual
        request traffic, not by load/swap times — a hot adapter that
        never reloads would otherwise look permanently stale."""
        with self._lora_lock:
            meta = self._lora_meta.get(model_id)
            if meta is not None:
                meta["last_used"] = time.monotonic()

    def adapter_salt_of(self, model_id: str | None) -> int:
        """KV salt of a RESIDENT adapter (0 = base / not resident) —
        the prefix-store miss path keys its directory lookup with
        this."""
        if model_id is None or self._lora_banks is None:
            return 0
        with self._lora_lock:
            meta = self._lora_meta.get(model_id)
            return meta["salt"] if meta else 0

    def _resolve_adapter(self, req: _Request, lane: int) -> bool:
        """Admission-time model_id → bank-slot resolution (loop
        thread).  Marks the LANE in _adapters under the lora lock
        BEFORE any block work, so a concurrent load_adapter can never
        evict the slot this admission is about to decode with (the
        mark is undone if block reservation fails).  A missing adapter
        — never loaded, or evicted since the server's residency check
        — fails the ONE request with AdapterLoadError: reject early,
        never wedge the loop."""
        with self._lora_lock:
            slot = self._lora_map.get(req.model_id)
            if slot is not None:
                meta = self._lora_meta[req.model_id]
                req.lora_slot = slot
                req.salt = meta["salt"]
                meta["last_used"] = time.monotonic()
                self._adapters[lane] = slot
                return True
        req.emit(None)
        if not req.future.done():
            req.future.set_exception(AdapterLoadError(
                "adapter not resident at admission",
                model_id=req.model_id, deployment=self.name,
                reason="not_resident"))
        return False

    def update_weights(self, refs, version: int | None = None) -> int:
        """Stage a fresh policy param tree for LIVE weight sync (the
        online-RLHF loop): the engine loop swaps `self.params` in
        BETWEEN decode sync windows — never mid-block, never draining a
        request — so generation replicas keep decoding while training
        advances the policy.  In-flight completions simply continue
        under the new weights from their next window (the bounded
        off-policy staleness the RLHF trainer's `max_weight_lag`
        accounts for).

        `refs` may be the param tree itself (host or device arrays), ONE
        ObjectRef to such a tree, or a list of ObjectRefs (the
        object-plane broadcast shapes) — resolved HERE on the caller's
        thread, never on the engine loop.  The tree must match the
        resident params' structure and leaf shapes (validated here, at
        the API edge — a mismatch inside the jitted decode would kill
        every tenant); leaves are cast to the resident dtypes at swap so
        the ONE compiled decode program stays valid.

        The swap also FLUSHES the radix prefix cache and generation-
        gates pending commits: every cached page holds KV computed
        under the old policy, and a post-swap prompt match against it
        would silently attend stale values (recurring RLHF prompts hit
        this constantly).  Group sharing within one rollout round is
        unaffected — leaders commit and followers match under the same
        generation.

        Thread-safe; latest staged version wins if the loop hasn't
        swapped yet.  Returns the staged version.  Kill switch
        RAY_TPU_RL_WEIGHT_SYNC=0 (read per call — same-run freeze-policy
        A/B) drops the update and returns the CURRENT version;
        `stats()["weight_version"]` is how callers observe propagation
        either way."""
        if not _env_on("RAY_TPU_RL_WEIGHT_SYNC"):
            with self._weights_lock:
                self.weight_syncs_skipped += 1
                return self.weight_version
        import jax

        tree = refs
        from ray_tpu.object_ref import ObjectRef

        if isinstance(tree, ObjectRef):
            import ray_tpu

            tree = ray_tpu.get(tree)
        elif (isinstance(tree, (list, tuple)) and tree
                and all(isinstance(r, ObjectRef) for r in tree)):
            import ray_tpu

            got = ray_tpu.get(list(tree))
            if len(got) == 1:
                tree = got[0]
            elif all(isinstance(g, dict) for g in got):
                # Sharded object-plane push: each ref carries a
                # disjoint top-level slice of the param dict (e.g.
                # embed / layers / lm_head as separate objects).
                tree = {}
                for g in got:
                    tree.update(g)
            else:
                raise ValueError(
                    "update_weights: a multi-ref push must resolve to "
                    "dict shards that merge into the param tree; got "
                    f"{[type(g).__name__ for g in got]}")
        new_leaves, new_def = jax.tree_util.tree_flatten(tree)
        cur_leaves, cur_def = jax.tree_util.tree_flatten(self.params)
        if new_def != cur_def:
            raise ValueError(
                "update_weights: param tree structure does not match "
                f"the engine's ({new_def} vs {cur_def})")
        for i, (a, b) in enumerate(zip(new_leaves, cur_leaves)):
            if tuple(getattr(a, "shape", ())) != tuple(b.shape):
                raise ValueError(
                    f"update_weights: leaf {i} shape "
                    f"{getattr(a, 'shape', ())} != resident {b.shape} "
                    "(wrong model config?)")
        with self._weights_lock:
            if version is None:
                version = max(self.weight_version,
                              self._staged_version) + 1
            # The stage timestamp travels WITH the staged tuple: a
            # concurrent re-stage must not corrupt the previous swap's
            # stage→visible latency measurement.
            self._staged_weights = (version, tree, time.perf_counter())
            self._staged_version = version
        self._wake.set()        # idle engines swap promptly too
        return version

    def _maybe_swap_weights(self) -> None:
        """Engine-loop half of update_weights: apply the newest staged
        tree, if any.  Runs at the top of every loop iteration — i.e.
        between decode sync windows — so an in-flight request's decode
        stalls at most one window behind a weight push."""
        with self._weights_lock:
            staged, self._staged_weights = self._staged_weights, None
        if staged is None:
            return
        import jax
        import jax.numpy as jnp

        version, tree, staged_t = staged
        # Cast to resident dtypes (bf16 engines fed fp32 learner
        # trees): the compiled decode program's signature must not
        # change under a swap.
        new_params = jax.tree.map(
            lambda new, old: jnp.asarray(new, old.dtype), tree,
            self.params)
        # Publish tree + version ATOMICALLY (params_snapshot takes the
        # same lock): a scorer must never label logprobs computed under
        # one tree with the other's version.
        with self._weights_lock:
            self.params = new_params
            self.weight_version = version
        self.weight_updates += 1
        if self._mgr is not None:
            # Cached KV belongs to the OLD policy: flush the radix tree
            # (refcount-0 pages free now; in-flight readers finish under
            # the documented staleness) and gate pending commits behind
            # a fresh generation.
            self._cache_gen += 1
            self._mgr.flush()
            with self._demote_lock:
                # Declined-leaf memory belongs to the flushed tree.
                self._demote_skip.clear()
        self.last_weight_sync_ms = (time.perf_counter()
                                    - staged_t) * 1000.0

    def params_snapshot(self):
        """Consistent (params, weight_version) pair for trajectory
        scoring: the swap publishes both under the weights lock, so a
        reader can never see the new tree labeled with the old version
        (or vice versa)."""
        with self._weights_lock:
            return self.params, self.weight_version

    def warmup(self, buckets: list[int] | None = None) -> None:
        """Pre-compile the decode program and prefill buckets so the first
        real request doesn't pay XLA compile time in its TTFT (the
        standard TPU-serving warmup discipline).  Warmup prompts are
        capped by the paged pool's capacity — a pool sized below one
        full max_len span (the very configurations paging enables) must
        not make warmup trip its own admission check.  Warmup traffic
        bypasses the prefix cache (_cache_ok=False): each bucket's
        ramp prompt is a prefix of the next one's, and matching it
        would compile the suffix programs instead of the full-prefill
        bucket programs warmup exists to build."""
        cap = self.max_len - 1
        if getattr(self, "page", None):
            cap = min(cap, (self.n_pages - 1) * self.page - 1)
        for b in buckets or self._buckets:
            n = min(b, cap)
            if n >= 1:
                self.generate(list(range(1, n + 1)), max_new_tokens=1,
                              _cache_ok=False)

    def set_sync_window(self, k: int | None) -> int:
        """Set the live decode sync-window size (overload degradation:
        smaller windows admit/eos-check more often, bounding how long a
        queued request waits behind a running block, at some
        amortization cost).  None restores the configured
        steps_per_sync.  Takes effect at the next window boundary (the
        loop reads it between blocks); each distinct size compiles its
        own cached decode program.  Token streams are UNCHANGED by the
        window size — sampling keys fold in the per-request generation
        index, not the window phase."""
        k = self.steps_per_sync if not k \
            else max(1, min(int(k), self.steps_per_sync))
        if k != self._k_live:
            if k < self.steps_per_sync:
                self.sync_window_shrinks += 1
            self._k_live = k
            self._wake.set()
        return k

    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="llm-engine", daemon=True)
            self._thread.start()
            self._register_memledger_provider()

    def _register_memledger_provider(self) -> None:
        """Attach this engine's resident HBM KV pool to the cluster
        memory harvest (tier "hbm" rows next to the arena tiers): used
        bytes = non-free pool blocks x bytes per page, from the same
        BlockManager accounting the radix cache runs on."""
        if self._mgr is None:
            return
        import jax

        try:
            pool_bytes = int(sum(
                x.size * x.dtype.itemsize
                for x in jax.tree_util.tree_leaves(self.cache)))
        except Exception:  # noqa: BLE001 - exotic cache leaves
            pool_bytes = 0
        per_page = pool_bytes // max(1, self.n_pages)
        lora_bytes = 0
        if self._lora_banks is not None:
            lora_bytes = int(sum(
                x.size * x.dtype.itemsize
                for t in self._lora_banks.values() for x in t.values()))
        self._memledger_provider = f"llm:{self.name}:{id(self):x}"

        def _rows():
            st = self._mgr.stats()
            used = st["n_blocks"] - st["free"]
            rows = [{"object_id": f"kvpool:{self.name}",
                     "size": used * per_page, "tag": "hbm_kv",
                     "tier": "hbm",
                     "callsite": f"serve/llm.py engine {self.name}",
                     "pool_bytes": pool_bytes,
                     "blocks_used": used,
                     "blocks_total": st["n_blocks"],
                     "blocks_cached": st["cached"]}]
            if lora_bytes:
                rows.append({
                    "object_id": f"lorabanks:{self.name}",
                    "size": lora_bytes, "tag": "lora_banks",
                    "tier": "hbm",
                    "callsite": f"serve/llm.py engine {self.name}",
                    "slots": self.lora_slots, "rank": self.lora_rank})
            return rows

        memledger.register_provider(self._memledger_provider, _rows)

    def stop(self) -> None:
        if getattr(self, "_memledger_provider", None):
            memledger.unregister_provider(self._memledger_provider)
            self._memledger_provider = None
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        if self._export_thread is not None:
            # Sentinel AFTER the loop stopped: pending exports drain in
            # order, then the thread exits.
            self._export_q.put(None)
            self._export_thread.join(timeout=10.0)
            self._export_thread = None
            self._export_q = None

    def abort_pending(self, exc: BaseException) -> None:
        """Fail every queued and in-flight request (call AFTER stop():
        the loop thread must not be racing the slot table).  A stopped
        engine would otherwise hang their futures forever — the replica
        reconfigure path swaps engines mid-traffic."""
        self._drain_requests(exc)

    def _drain_requests(self, exc: BaseException) -> None:
        # Queued grafts hang their callers' 60s waits if the loop dies
        # with them unapplied — fail them like every pending request.
        while True:
            try:
                *_rest, fut = self._graft_q.get_nowait()
            except queue.Empty:
                break
            if not fut.done():
                fut.set_exception(exc)
        for req in list(self._pending):
            req.emit(None)
            if not req.future.done():
                req.future.set_exception(exc)
        self._pending.clear()
        for i, req in enumerate(self._slots):
            if req is not None:
                req.emit(None)
                if not req.future.done():
                    req.future.set_exception(exc)
            self._slots[i] = None
        while True:
            try:
                req = self._waiting.get_nowait()
            except queue.Empty:
                break
            req.emit(None)
            if not req.future.done():
                req.future.set_exception(exc)

    # -------------------------------------------------------------- engine
    def _apply_grafts(self) -> None:
        """Engine-loop half of kv_graft: allocate, scatter, commit,
        release.  Runs right after the weight swap so the version check
        sees the tree the commit would land in.  A failed graft (the
        serve.prefix_graft failpoint, pool pressure) fails ITS future
        only — the loop and every tenant survive."""
        import jax.numpy as jnp

        from ray_tpu import failpoints

        while True:
            try:
                tokens, kv, n, wv, salt, fut = \
                    self._graft_q.get_nowait()
            except queue.Empty:
                return
            try:
                if failpoints.ACTIVE:
                    failpoints.fire("serve.prefix_graft")
                if self._mgr is None or not self._prefix_cache:
                    out = {"grafted": 0, "reason": "no_prefix_cache"}
                elif wv is not None and wv != self.weight_version:
                    # Stored KV from another policy version: grafting
                    # it would silently attend stale values.
                    out = {"grafted": 0, "reason": "stale_version"}
                else:
                    blocks = self._mgr.allocate(n)
                    if blocks is None:
                        out = {"grafted": 0, "reason": "no_blocks"}
                    else:
                        m = _pow2(n)
                        ids = list(blocks) + [0] * (m - n)
                        if m > n:
                            pad = np.zeros(
                                kv.shape[:2] + (m - n,) + kv.shape[3:],
                                kv.dtype)
                            kv = np.concatenate([kv, pad], axis=2)
                        self.cache = self._graft_pages(
                            self.cache, jnp.asarray(kv),
                            jnp.asarray(ids, jnp.int32))
                        # Commit BEFORE release: the blocks become
                        # cached-evictable instead of freed (the
                        # _release_slot discipline).
                        self._mgr.commit(tokens, blocks, salt=salt)
                        self._mgr.release(blocks)
                        self.kv_grafts += 1
                        self.graft_tokens += n * self.page
                        out = {"grafted": n, "tokens": n * self.page}
            except BaseException as e:  # noqa: BLE001 - injected faults
                if not fut.done():
                    fut.set_exception(e)
                continue
            if not fut.done():
                fut.set_result(out)

    def _ensure_export_thread(self) -> queue.Queue:
        if self._export_q is None:
            self._export_q = queue.Queue()
            self._export_thread = threading.Thread(
                target=self._export_loop, name="llm-kv-export",
                daemon=True)
            self._export_thread.start()
        return self._export_q

    def _maybe_demote(self) -> None:
        """Loop-side demotion scan (tier-1 → tier-2): pick cold
        refcount-0 radix leaves (BlockManager.demote_scan), dispatch
        ONE device gather per candidate covering the whole path
        root..leaf, and hand the host fetch + publish to the export
        thread — the loop never blocks on the tunnel round trip.
        Throttled by period and in-flight cap; no-op until a server
        installs the callback, and gated per scan by the
        RAY_TPU_PREFIX_STORE kill switch."""
        cb = self._demote_cb
        if (cb is None or self._mgr is None or not self._prefix_cache
                or not self.paged):
            return
        knobs = self._demote_knobs
        now = time.monotonic()
        if now - self._demote_t < knobs["period_s"]:
            return
        self._demote_t = now
        with self._demote_lock:
            if self._demote_inflight >= knobs["max_inflight"]:
                return
            budget = knobs["max_inflight"] - self._demote_inflight
            exclude = set(self._demote_skip)
        from ray_tpu.serve.kv_router import prefix_store_on

        if not prefix_store_on():
            return
        cands = self._mgr.demote_scan(
            limit=min(knobs["limit"], budget),
            min_idle=knobs["min_idle"], watermark=knobs["watermark"],
            exclude=exclude)
        if not cands:
            return
        import jax.numpy as jnp

        q = self._ensure_export_thread()
        gen, wv = self._cache_gen, self.weight_version
        for c in cands:
            n = c["depth"]
            ids_p = list(c["blocks"]) + [0] * (_pow2(n) - n)
            arr = self._gather_kv(self.cache["k"], self.cache["v"],
                                  jnp.asarray(ids_p, jnp.int32))
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
            with self._demote_lock:
                self._demote_inflight += 1
            q.put(("demote", c, arr, gen, wv))

    def _demote_one(self, c: dict, arr, gen: int, wv: int) -> None:
        """Export-thread half of one demotion: materialize the host KV,
        publish to the store, then finish the manager-side accounting
        (pins released either way; the tier-1 leaf drops only when
        tier 2 really holds the entry AND no weight swap invalidated
        the KV mid-flight)."""
        published = False
        try:
            host = np.ascontiguousarray(
                np.asarray(arr)[:, :, :c["depth"]])
        except BaseException:  # noqa: BLE001 - device fault
            self.demote_failures += 1
            host = None
        if host is not None and gen == self._cache_gen:
            from ray_tpu import failpoints

            try:
                if failpoints.ACTIVE:
                    # The mid-demotion fault window: a crash here dies
                    # BETWEEN the KV gather and the store registration
                    # — the chaos shape the accounting must survive.
                    failpoints.fire("serve.prefix_demote")
                published = bool(self._demote_cb(dict(
                    tokens=c["tokens"], kv=host, hashes=c["hashes"],
                    depth=c["depth"], page=self.page,
                    weight_version=wv, salt=c.get("salt", 0))))
            except BaseException:  # noqa: BLE001 - injected faults
                self.demote_failures += 1
            if not published:
                with self._demote_lock:
                    self._demote_skip.add(c["hash"])
                    if len(self._demote_skip) > 4096:
                        self._demote_skip.clear()
        self._mgr.demote_finish(
            c["leaf"], c["blocks"],
            drop=published and gen == self._cache_gen)
        if published:
            self.demote_published += 1
        with self._demote_lock:
            self._demote_inflight -= 1
        self._wake.set()

    def _reserve_blocks(self, req: _Request,
                        copies: list[tuple[int, int]]) -> bool:
        """Admission-time block reservation: match the longest cached
        prefix, then allocate enough fresh blocks to cover the prompt
        plus one decode window (the full remaining span when preemption
        is off — the legacy admission contract).  Returns False with no
        net state change when the pool can't cover it."""
        mgr = self._mgr
        seq = req.prompt + req.tokens       # resume includes generated
        total = len(seq)
        remaining = req.max_new_tokens - len(req.tokens)
        # Imported-KV requests never match the local cache: their pages
        # arrive by scatter and must be fresh private blocks.
        matched = mgr.match(seq, salt=req.salt) \
            if (req.cache_ok and req.import_kv is None) else []
        matched_tokens = len(matched) * self.page
        cover = total + (min(remaining, self._k_live)
                         if self._preempt_on else remaining)
        need = max(0, -(-cover // self.page) - len(matched))
        fresh = mgr.allocate(need)
        if fresh is None:
            mgr.release(matched)
            return False
        pages = matched + fresh
        if matched_tokens >= total:
            # Whole prompt cached: recompute only the LAST token (its
            # logits seed the first sample).  That one write lands in
            # the block holding position total-1 — the final MATCHED
            # block, shared and sealed — so fork it first (COW).
            li = (total - 1) // self.page
            nb, copied = mgr.cow(pages[li])
            if nb < 0:
                mgr.release(pages)
                return False
            if copied:
                copies.append((pages[li], nb))
                pages[li] = nb
            req.prefill_from = total - 1
        else:
            req.prefill_from = matched_tokens
        req.pages = pages
        return True

    def _admit(self) -> None:
        """Prefill a whole wave of waiting requests in ONE device call;
        one batched fetch materializes their first tokens."""
        import jax.numpy as jnp

        while True:        # drain arrivals behind any preempted requests
            try:
                self._pending.append(self._waiting.get_nowait())
            except queue.Empty:
                break
        wave: list[tuple[int, _Request]] = []    # (slot, request)
        copies: list[tuple[int, int]] = []       # COW (src, dst) pages
        grace_deadline = None
        while True:
            free = next((i for i, s in enumerate(self._slots)
                         if s is None), None)
            if free is None:
                break
            if not self._pending:
                # Burst coalescing: submissions race admission, and a
                # wave that launches a beat early strands the rest of
                # the burst behind a full prefill+sync round (~120ms
                # of loaded TTFT on a tunneled chip).  Once at least
                # one request is in hand, linger a few ms so the
                # whole burst rides ONE wave; idle requests never
                # wait (no linger on an empty wave).
                try:
                    self._pending.append(self._waiting.get_nowait())
                    continue
                except queue.Empty:
                    pass
                if not wave:
                    break
                if grace_deadline is None:
                    with self._inflight_lock:
                        busy = self._inflight_submits > 0
                        last_t = self._last_submit_t
                    if not busy and last_t <= max(
                            r.submitted_at for _, r in wave):
                        # Lone request(s): nobody is mid-submit and
                        # nothing arrived after the requests already
                        # in hand — launch NOW instead of lingering
                        # the full grace ("idle requests never
                        # wait"); bursts still coalesce because a
                        # racing submit moves _last_submit_t.
                        break
                    grace_deadline = time.perf_counter() + 0.005
                rem = grace_deadline - time.perf_counter()
                if rem <= 0:
                    break
                try:
                    self._pending.append(self._waiting.get(timeout=rem))
                except queue.Empty:
                    break
                continue
            req = self._pending[0]
            if req.model_id is not None \
                    and not self._resolve_adapter(req, free):
                # Unknown/evicted adapter: fail THIS request early and
                # keep admitting — an adapter miss must never become a
                # head-of-line barrier.
                self._pending.popleft()
                continue
            if self.paged:
                # The block pool is the admission control: the FRONT
                # request blocks FIFO when free + evictable can't cover
                # it (vLLM-style KV backpressure; nothing skips past).
                if not self._reserve_blocks(req, copies):
                    if req.lora_slot:
                        # Undo the lane's slot mark — the request is
                        # NOT decoding; its adapter stays evictable.
                        with self._lora_lock:
                            self._adapters[free] = 0
                    break
                self._table[free, :] = 0
                self._table[free, :len(req.pages)] = req.pages
                self._table_dirty = True
            self._pending.popleft()
            req.slot = free
            req.admitted_at = time.perf_counter()
            req.admitted_wall = time.time()
            req.cache_gen = self._cache_gen
            self._slots[free] = req
            self._temps[free] = req.temperature
            self._seeds[free] = req.sample_seed
            wave.append((free, req))
        if not wave:
            return
        # Migrated-KV admissions scatter their imported pages instead of
        # prefilling; their first token was already produced (and
        # delivered) by the exporting engine, so they skip the
        # first-token fetch below entirely.
        imports = [(s, r) for s, r in wave if r.import_kv is not None]
        wave = [(s, r) for s, r in wave if r.import_kv is None]
        for slot, req in imports:
            t_imp0 = time.time()
            kv_len = req.import_len
            self._apply_import(slot, req)
            if req.first_token_at is None:
                req.first_token_at = time.perf_counter()
            if tracing.ENABLED and req.trace is not None:
                tracing.emit("llm.queue", req.t0_wall, req.admitted_wall,
                             ctx=req.trace)
                tracing.emit("llm.kv_import", t_imp0, ctx=req.trace,
                             attrs={"kv_len": kv_len,
                                    "pages": len(req.pages)})
            if self._done(req):
                self._finish(slot)
        if not wave:
            return
        if copies:
            # Materialize COW copies before any prefill reads/writes the
            # forked pages (ordering rides the donated-cache dependency).
            pairs = copies + [(0, 0)] * (_pow2(len(copies))
                                         - len(copies))
            self.cache = self._copy_pages(
                self.cache, jnp.asarray([s for s, _ in pairs], jnp.int32),
                jnp.asarray([d for _, d in pairs], jnp.int32))
        # Sub-waves of <=_chunk requests: dispatch every chunk's forward
        # (and, paged, its separate scatter program) back-to-back, THEN
        # fetch first tokens — chunk 1's round trip overlaps chunk 2's
        # compute, so a big burst's p50 TTFT tracks one RTT plus HALF
        # the total prefill instead of all of it.
        pending_waves = []        # (chunk, nxt_device, dispatch wall t)
        for c0 in range(0, len(wave), self._chunk):
            chunk = wave[c0:c0 + self._chunk]
            t_disp = time.time()
            if self.paged and any(r.prefill_from > 0 for _, r in chunk):
                nxt = self._prefill_chunk_suffix(chunk)
            else:
                nxt = self._prefill_chunk_full(chunk)
            pending_waves.append((chunk, nxt, t_disp))
        for _, nxt, _t in pending_waves:
            try:
                nxt.copy_to_host_async()
            except AttributeError:
                pass
        for chunk, nxt, t_disp in pending_waves:
            firsts = np.asarray(nxt)[:len(chunk)]
            now = time.perf_counter()
            now_wall = time.time()
            for (slot, req), first in zip(chunk, firsts):
                if req.first_token_at is None:
                    req.first_token_at = now
                req.tokens.append(int(first))
                req.emit(int(first))
                if self._done(req):
                    self._finish(slot)
            if not tracing.ENABLED:
                continue
            for slot, req in chunk:
                if req.trace is None:
                    continue
                # The request's engine-side TTFT anatomy: queue (submit
                # → slot), prefill (chunk dispatch → first tokens on
                # host; chunk-mates share the device call, so they
                # share the window), first-token marker.
                tracing.emit("llm.queue", req.t0_wall,
                             req.admitted_wall, ctx=req.trace)
                if req.model_id is not None:
                    # The adapter APPLY leg: this request's decode
                    # gathers bank slot `lora_slot` from here on.
                    tracing.emit(
                        "serve.adapter_apply", req.admitted_wall,
                        req.admitted_wall, ctx=req.trace,
                        attrs={"model_id": req.model_id,
                               "slot": req.lora_slot})
                tracing.emit(
                    "llm.prefill", t_disp, now_wall, ctx=req.trace,
                    attrs={"prompt_tokens": len(req.prompt),
                           "prefill_from": req.prefill_from,
                           "cached_tokens": req.prefill_from})
                tracing.emit(
                    "llm.first_token", now_wall, now_wall,
                    ctx=req.trace,
                    attrs={"ttft_ms": round(
                        (req.first_token_at - req.submitted_at)
                        * 1000, 1)})

    def _prefill_chunk_full(self, chunk):
        """Full-prompt prefill (no cached prefix anywhere in the chunk):
        the original bucketed wave path, byte-for-byte."""
        import jax.numpy as jnp

        W = len(chunk)
        bucket = next(b for b in self._buckets
                      if b >= max(len(r.prompt) + len(r.tokens)
                                  for _, r in chunk))
        # Pad by duplicating the last row: the duplicate writes the
        # same slot with the same data, so correctness is
        # unaffected.  Width is BUCKETED (1 / 8 / _chunk), not
        # always max_batch: an idle single request padded to a
        # 64-wide wave paid 64x the prefill FLOPs it needed — the
        # round-3 idle-TTFT regression.  Few widths × few length
        # buckets keeps the compile count small.
        padded_w = next(w for w in self._width_buckets if w >= W)
        tokens = np.zeros((padded_w, bucket), np.int32)
        true_lens = np.ones((padded_w,), np.int32)
        slots = np.zeros((padded_w,), np.int32)
        temps = np.zeros((padded_w,), np.float32)
        seeds = np.zeros((padded_w,), np.int32)
        starts = np.zeros((padded_w,), np.int32)
        lidx = np.zeros((padded_w,), np.int32)
        for j in range(padded_w):
            slot, req = chunk[min(j, W - 1)]
            seq = req.prompt + req.tokens   # resume: recompute full seq
            tokens[j, :len(seq)] = seq
            true_lens[j] = len(seq)
            slots[j] = slot
            temps[j] = req.temperature
            seeds[j] = req.sample_seed
            starts[j] = len(req.tokens)
            lidx[j] = req.lora_slot
        for _, req in chunk:
            self.prefill_tokens += len(req.prompt) + len(req.tokens)
        slots_dev = jnp.asarray(slots)
        lens_dev = jnp.asarray(true_lens)
        if self.paged:
            cols = np.arange(bucket) // self.page
            page_ids = self._table[slots][:, cols]  # [padded_w, bkt]
            rows = np.tile(
                np.arange(bucket, dtype=np.int32) % self.page,
                (padded_w, 1))
            nxt, ks, vs = self._prefill_fwd(
                self.params, jnp.asarray(tokens), lens_dev,
                slots_dev, jnp.asarray(temps), jnp.asarray(seeds),
                jnp.asarray(starts), self._lora_args(lidx))
            self.cache = self._scatter_pages(
                self.cache, ks, vs, jnp.asarray(page_ids),
                jnp.asarray(rows), slots_dev, lens_dev)
        else:
            nxt, self.cache = self._prefill(
                self.params, self.cache, jnp.asarray(tokens),
                lens_dev, slots_dev, jnp.asarray(temps),
                jnp.asarray(seeds), jnp.asarray(starts))
        # Duplicate padding rows target the same slot + same token.
        self._cur_dev = self._cur_dev.at[slots_dev].set(nxt)
        return nxt

    def _prefill_chunk_suffix(self, chunk):
        """Prefix-cache prefill: forward only each request's uncached
        SUFFIX, attending the cached prefix through the page pool; the
        suffix KV scatters at its absolute positions (prefill_from is a
        page multiple — or the COW'd private page for a full match — so
        shared pages are never written)."""
        import jax.numpy as jnp

        W = len(chunk)
        suf = [len(r.prompt) + len(r.tokens) - r.prefill_from
               for _, r in chunk]
        bucket = next(b for b in self._buckets if b >= max(suf))
        padded_w = next(w for w in self._width_buckets if w >= W)
        tokens = np.zeros((padded_w, bucket), np.int32)
        pos0 = np.zeros((padded_w,), np.int32)
        last_idx = np.zeros((padded_w,), np.int32)
        true_lens = np.ones((padded_w,), np.int32)
        slots = np.zeros((padded_w,), np.int32)
        temps = np.zeros((padded_w,), np.float32)
        seeds = np.zeros((padded_w,), np.int32)
        starts = np.zeros((padded_w,), np.int32)
        lidx = np.zeros((padded_w,), np.int32)
        for j in range(padded_w):
            slot, req = chunk[min(j, W - 1)]
            seq = req.prompt + req.tokens
            suffix = seq[req.prefill_from:]
            tokens[j, :len(suffix)] = suffix
            pos0[j] = req.prefill_from
            last_idx[j] = len(suffix) - 1
            true_lens[j] = len(seq)
            slots[j] = slot
            temps[j] = req.temperature
            seeds[j] = req.sample_seed
            starts[j] = len(req.tokens)
            lidx[j] = req.lora_slot
        for _, req in chunk:
            self.prefill_tokens += (len(req.prompt) + len(req.tokens)
                                    - req.prefill_from)
        # Scatter coordinates at ABSOLUTE positions: suffix token p of
        # slot b lands at pos0[b] + p; positions past the allocated
        # span resolve to the trash page via the zeroed table columns.
        apos = np.minimum(pos0[:, None] + np.arange(bucket)[None, :],
                          self._maxp * self.page - 1)
        cols = (apos // self.page).astype(np.int64)
        page_ids = np.take_along_axis(self._table[slots], cols, axis=1)
        rows = (apos % self.page).astype(np.int32)
        slots_dev = jnp.asarray(slots)
        nxt, ks, vs = self._prefill_suffix(
            self.params, self.cache["k"], self.cache["v"],
            jnp.asarray(tokens), jnp.asarray(pos0),
            jnp.asarray(self._table[slots]), jnp.asarray(last_idx),
            jnp.asarray(temps), jnp.asarray(seeds), jnp.asarray(starts),
            self._lora_args(lidx))
        self.cache = self._scatter_pages_coord(
            self.cache, ks, vs, jnp.asarray(page_ids),
            jnp.asarray(rows), slots_dev, jnp.asarray(true_lens))
        self._cur_dev = self._cur_dev.at[slots_dev].set(nxt)
        return nxt

    def _apply_import(self, slot: int, req: _Request) -> None:
        """Scatter a migrated request's KV pages into its freshly
        reserved blocks and seed the slot's position/current token —
        the admission-time half of kv_import().  The (possibly
        arena-view) payload is dropped immediately after the device
        copy so a migrated object's pin never outlives its single
        read."""
        import jax.numpy as jnp

        n_imp = -(-req.import_len // self.page)
        ids = req.pages[:n_imp]
        kv = req.import_kv
        m = _pow2(n_imp)
        if m > n_imp:
            # Pad ids with the trash page (writes there are garbage by
            # contract) so import widths compile per power of two.
            pad = np.zeros(kv.shape[:2] + (m - n_imp,) + kv.shape[3:],
                           kv.dtype)
            kv = np.concatenate([kv, pad], axis=2)
            ids = list(ids) + [0] * (m - n_imp)
        self.cache, self._cur_dev = self._import_pages(
            self.cache, self._cur_dev, jnp.asarray(kv),
            jnp.asarray(ids, jnp.int32),
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(req.import_len, jnp.int32),
            jnp.asarray(req.tokens[-1], jnp.int32))
        req.import_kv = None
        self.kv_imports += 1

    def _finish_export(self, slot: int, req: _Request) -> None:
        """Finish a prefill_only request: dispatch the page gather for
        migration and hand the HOST FETCH to the export thread — a
        synchronous device→host read here would stall the engine loop
        (and every co-resident request's admission) for the full
        tunnel round trip per migration.  The covered blocks are
        export-pinned (BlockManager.export_blocks) so the
        commit/release in _release_slot — which must run on THIS
        thread, it owns the slot table — cannot free them before the
        fetch lands; refcounted pins also make them eviction-proof."""
        import jax.numpy as jnp

        from ray_tpu import failpoints

        ids = None
        try:
            kv_len = len(req.prompt) + len(req.tokens) - 1
            ids = self._mgr.export_blocks(req.pages, kv_len)
            # The failpoint models a fault INSIDE the pinned window —
            # the hard case: the export pins must be dropped on the
            # way out or the pool silently shrinks per failed export.
            if failpoints.ACTIVE:
                failpoints.fire("serve.kv_export")
            n = len(ids)
            m = _pow2(n)
            ids_p = list(ids) + [0] * (m - n)
            # Async dispatch + async copy: the loop moves on while the
            # device computes and the bytes stream to the host.
            arr = self._gather_kv(self.cache["k"], self.cache["v"],
                                  jnp.asarray(ids_p, jnp.int32))
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        except BaseException as e:  # noqa: BLE001 - injected faults
            # A failed export (serve.kv_export failpoint, OOM on the
            # gather) must not kill the engine loop NOR leak anything:
            # drop the export pins AND the request's own refs, fail the
            # one future, and let the server fall back to serving
            # locally.
            if ids is not None:
                self._mgr.release(ids)
            self._release_slot(slot, req)
            req.emit(None)
            if not req.future.done():
                req.future.set_exception(e)
            return
        self._release_slot(slot, req)
        # The prefill engine produced the request's REAL first token —
        # observe its TTFT here (the export early-return in _finish
        # skips the unified-path observation, and the decode side must
        # not re-observe a near-zero one).
        self._observe_done(req, time.perf_counter())
        self._ensure_export_thread().put(
            ("export", req, arr, ids, kv_len, n))

    def _export_loop(self) -> None:
        """Materializes device→host payloads off the engine loop: KV
        migrations (kv_export) and prefix-store demotions both fetch
        here so the decode loop never blocks on a tunnel round trip."""
        while True:
            item = self._export_q.get()
            if item is None:
                return
            if item[0] == "demote":
                self._demote_one(*item[1:])
            else:
                self._export_one(*item[1:])

    def _export_one(self, req, arr, ids, kv_len: int, n: int) -> None:
        """One kv_export materialization: the stacked
        [2, L, n, kvh, page, hd] host array covers every position whose
        KV has been written (the newest token's hasn't — the importer
        recomputes it as its first decode step); resolves the request's
        future and drops the export pins."""
        t_exp0 = time.time()
        try:
            # Contiguous copy of the REAL payload: a bare slice
            # would pin the whole pow-2-padded buffer and force
            # put() to copy the non-contiguous view again.
            host = np.ascontiguousarray(np.asarray(arr)[:, :, :n])
        except BaseException as e:  # noqa: BLE001
            self._mgr.release(ids)
            req.emit(None)
            if not req.future.done():
                req.future.set_exception(e)
            return
        self._mgr.release(ids)
        self.kv_exports += 1
        if tracing.ENABLED and req.trace is not None:
            # The device→host KV fetch of one migration — the
            # export half of the kv_export→put→pull→kv_import leg.
            tracing.emit("llm.kv_export", t_exp0, ctx=req.trace,
                         attrs={"bytes": host.nbytes,
                                "kv_len": kv_len, "pages": n})
        now = time.perf_counter()
        req.emit(None)
        if not req.future.done():
            req.future.set_result({
                "tokens": req.tokens,
                "ttft_s": (req.first_token_at or now)
                - req.submitted_at,
                "total_s": now - req.submitted_at,
                "kv_export": {
                    "kv": host, "len": kv_len, "page": self.page,
                    "sample_seed": req.sample_seed,
                    "tokens": list(req.tokens)},
            })

    def _done(self, req: _Request) -> bool:
        return (len(req.tokens) >= req.max_new_tokens
                or (req.eos_id is not None
                    and req.tokens[-1] == req.eos_id))

    def _release_slot(self, slot: int, req: _Request) -> None:
        """Commit the request's computed full blocks into the prefix
        cache, then drop its references (cached blocks stay resident
        but evictable; private ones free).  KV is valid only below
        prompt+tokens-1: the newest token's K/V hasn't been written,
        and rows past a lane's early finish hold trimmed overshoot."""
        if not (self.paged and req.pages):
            return
        kv_valid = len(req.prompt) + len(req.tokens) - 1
        if req.cache_ok and req.cache_gen == self._cache_gen:
            # A request admitted before a weight swap computed (some
            # of) its KV under the OLD policy — committing it would
            # repollute the freshly-flushed cache with stale pages.
            self._mgr.commit(req.prompt + req.tokens,
                             req.pages[:kv_valid // self.page],
                             salt=req.salt)
        self._mgr.release(req.pages)
        req.pages = []
        # The freed slot's future (garbage) decode writes go to the
        # trash page once the zeroed table row reaches the device
        # (next _admit or dirty refresh — both before the pages can
        # be re-issued to a new request).
        self._table[slot, :] = 0
        self._table_dirty = True

    def _finish(self, slot: int) -> None:
        req = self._slots[slot]
        self._slots[slot] = None
        self._adapters[slot] = 0      # the lane's adapter is evictable
        self.completed += 1
        if req.prefill_only and self.paged and req.pages \
                and not (req.eos_id is not None and req.tokens
                         and req.tokens[-1] == req.eos_id):
            # Export path: block release + table scrub happen here (the
            # loop owns both); the host fetch and future resolution ride
            # the export thread.  An eos-terminated request skips it —
            # generation is over, so gathering/fetching its KV would be
            # a full tunnel round trip for a payload nobody consumes
            # (the server returns the tokens directly when kv_export is
            # absent).
            self._finish_export(slot, req)
            return
        self._release_slot(slot, req)
        now = time.perf_counter()
        self._observe_done(req, now)
        req.emit(None)
        if not req.future.done():
            req.future.set_result({
                "tokens": req.tokens,
                "ttft_s": (req.first_token_at or now) - req.submitted_at,
                "total_s": now - req.submitted_at,
            })

    def _observe_done(self, req: _Request, now: float) -> None:
        """Feed the request's latency into the TTFT/TPOT/stage
        histograms (→ controller KV → dashboard /metrics as proper
        Prometheus histogram families).  A migrated decode-side request
        (import_len > 0) skips the TTFT/queue/prefill observations: its
        first_token_at is the IMPORT application, not a real first
        token — the prefill engine that produced the token observed
        the true TTFT (see _finish_export)."""
        try:
            m = _engine_metrics()
        except Exception:  # noqa: BLE001 - metrics must not stop decode
            return
        ft = req.first_token_at
        if ft is None:
            return
        tags = {"engine": self.name}
        imported = req.import_len > 0
        if not imported:
            m["ttft"].observe((ft - req.submitted_at) * 1000.0, tags)
            self._slo_window.observe(
                "ttft_ms", (ft - req.submitted_at) * 1000.0)
        n = len(req.tokens)
        if n > 1 and now > ft:
            m["tpot"].observe((now - ft) * 1000.0 / (n - 1), tags)
        if req.admitted_at:
            st = m["stage"]
            if not imported:
                st.observe(
                    (req.admitted_at - req.submitted_at) * 1000.0,
                    {**tags, "stage": "queue"})
                st.observe((ft - req.admitted_at) * 1000.0,
                           {**tags, "stage": "prefill"})
                self._slo_window.observe(
                    "queue_ms",
                    (req.admitted_at - req.submitted_at) * 1000.0)
                self._slo_window.observe(
                    "prefill_ms", (ft - req.admitted_at) * 1000.0)
            if not req.prefill_only:
                # No decode ran on a prefill-only export — a ~0ms
                # sample here would drag the cross-engine decode
                # quantiles toward zero as migration volume grows.
                st.observe((now - ft) * 1000.0,
                           {**tags, "stage": "decode"})
                self._slo_window.observe("decode_ms",
                                         (now - ft) * 1000.0)

    def _preempt_slot(self, slot: int) -> None:
        """Evict a running request from its slot: its blocks go to the
        prefix cache (so recompute usually prefix-hits them if nobody
        claims the memory first) and it re-enters the pending queue at
        the FRONT.  Tokens already streamed stay valid — per-request
        sampling keys make the recomputed continuation identical."""
        req = self._slots[slot]
        self._slots[slot] = None
        self._temps[slot] = 0.0
        self._seeds[slot] = 0
        self._adapters[slot] = 0
        self._release_slot(slot, req)
        req.slot = -1
        req.preempted += 1
        self.preemptions += 1
        self._pending.appendleft(req)

    def _ensure_decode_blocks(self, k_win: int | None = None
                              ) -> list[int]:
        """Block-budget scheduling before each decode block: every
        active slot needs real pages under the next K merge positions.
        Oldest requests are funded first; when the pool (free +
        evictable) runs dry, the NEWEST active request is preempted and
        recomputed later — deterministic, and the oldest request can
        always make progress (its full span fits the pool by the
        submit-time check).  Returns the surviving active slots.
        `k_win` is the loop's snapshot of the sync window — funding and
        the decode call must agree on it (a concurrent set_sync_window
        between them must not leave the window underfunded)."""
        if k_win is None:
            k_win = self._k_live
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not self.paged or not active:
            return active
        for slot in sorted(active,
                           key=lambda i: self._slots[i].sample_seed):
            req = self._slots[slot]
            if req is None:                  # preempted this round
                continue
            total = len(req.prompt) + len(req.tokens)
            cover = min(total - 1 + k_win,
                        len(req.prompt) + req.max_new_tokens)
            need = -(-cover // self.page) - len(req.pages)
            if need <= 0:
                continue
            got = self._mgr.allocate(need)
            while got is None and self._preempt_on:
                victims = [i for i, s in enumerate(self._slots)
                           if s is not None]
                victim = max(victims,
                             key=lambda i: self._slots[i].sample_seed)
                self._preempt_slot(victim)
                if victim == slot:
                    break
                got = self._mgr.allocate(need)
            if got is None or self._slots[slot] is None:
                continue
            req.pages.extend(got)
            self._table[slot, :len(req.pages)] = req.pages
            self._table_dirty = True
        return [i for i, s in enumerate(self._slots) if s is not None]

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001
            # Fail every in-flight and waiting request: a silent thread
            # death would hang their futures forever, and the donated
            # cache is invalid after a failed call anyway.
            self._error = e
            self._drain_requests(e)
            self._stop.set()
            raise

    def _loop_inner(self) -> None:
        import jax.numpy as jnp

        while not self._stop.is_set():
            self._maybe_swap_weights()
            # Grafts apply right after the swap (the version check must
            # see the tree a commit would land in) and BEFORE admission
            # so the request that triggered the graft prefix-hits it.
            self._apply_grafts()
            self._admit()
            # ONE sync-window snapshot per iteration: funding and the
            # decode program must see the same K (set_sync_window may
            # race from a replica thread).
            k_win = self._k_live
            active = self._ensure_decode_blocks(k_win)
            self._maybe_demote()
            self._flush_metrics()
            if not active:
                if self._pending:
                    # Head-of-line request waiting on blocks with no
                    # active decode to free them: only finished-and-
                    # cached blocks can help — _admit retries (allocate
                    # evicts refcount-0 leaves), so just avoid a busy
                    # spin.
                    self._wake.wait(timeout=0.002)
                else:
                    self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            if self._table_dirty:
                self._table_dev = jnp.asarray(self._table) if self.paged \
                    else jnp.zeros((1, 1), jnp.int32)
                self._table_dirty = False
            starts = np.zeros((self.max_batch,), np.int32)
            for i in active:
                starts[i] = len(self._slots[i].tokens)
            win_traced = tracing.ENABLED and any(
                self._slots[i] is not None
                and self._slots[i].trace is not None for i in active)
            t_win0 = time.time() if win_traced else 0.0
            decode = self._decode_fns.get(k_win)
            if decode is None:
                decode = self._decode_fns.setdefault(
                    k_win, self._make_decode(k_win))
            seq, last, self.cache = decode(
                self.params, self.cache, self._cur_dev,
                jnp.asarray(self._temps), self._table_dev,
                jnp.asarray(self._seeds), jnp.asarray(starts),
                self._lora_args(self._adapters))
            self._cur_dev = last                # stays on device
            seq = np.asarray(seq)               # the ONE sync per block
            if win_traced:
                # One K-step decode window per traced co-resident
                # request: the window (dispatch → host sync) is the
                # decode-side unit of TTFT/TPOT attribution.
                t_win1 = time.time()
                for i in active:
                    r = self._slots[i]
                    if r is not None and r.trace is not None:
                        tracing.emit(
                            "llm.decode_window", t_win0, t_win1,
                            ctx=r.trace,
                            attrs={"steps": k_win,
                                   "weight_version":
                                   self.weight_version})
            for i in active:
                req = self._slots[i]
                if req is None:
                    continue
                for tok in seq[:, i]:
                    req.tokens.append(int(tok))
                    self.decode_tokens += 1
                    req.emit(int(tok))
                    if self._done(req):
                        # Trim K-step overshoot past EOS/max_new_tokens.
                        self._finish(i)
                        break

    def _flush_metrics(self, force: bool = False) -> None:
        """Export engine/cache counters as process metrics (→ controller
        KV → dashboard /metrics).  Counters flush as deltas against the
        last snapshot; throttled to ~1 Hz so the loop never stalls on
        the registry lock."""
        now = time.monotonic()
        if not force and now - self._metrics_t < 1.0:
            return
        try:
            m = _engine_metrics()
        except Exception:  # noqa: BLE001 - metrics must never stop decode
            return
        tags = {"engine": self.name}
        cur = {"prefill_tokens": self.prefill_tokens,
               "decode_tokens": self.decode_tokens,
               "preemptions": self.preemptions,
               "completed": self.completed,
               "weight_updates": self.weight_updates}
        if self._mgr is not None:
            cur["prefix_hit_tokens"] = self._mgr.hit_tokens
            cur["evictions"] = self._mgr.evictions
        with self._metrics_lock:
            self._metrics_t = now
            for key, val in cur.items():
                delta = val - self._metrics_last.get(key, 0)
                if delta > 0:
                    m[key].inc(delta, tags)
                self._metrics_last[key] = val
        m["occupancy"].set(
            sum(s is not None for s in self._slots) / self.max_batch,
            tags)
        m["queue_depth"].set(
            self._waiting.qsize() + len(self._pending), tags)
        m["weight_version"].set(float(self.weight_version), tags)
        if self._mgr is not None:
            m["free_blocks"].set(self._mgr.free_count(), tags)
            seen = self._mgr.hit_tokens + self.prefill_tokens
            m["hit_rate"].set(
                self._mgr.hit_tokens / seen if seen else 0.0, tags)

    def kv_check(self) -> dict:
        """Assert the block-state partition (test/ops probe): raises if
        any KV block is leaked or double-booked.  Shared by the serve
        replica's kv_check RPC and the RLHF rollout workers' post-chaos
        leak checks."""
        if self._mgr is None:
            return {"ok": True, "paged": False}
        self._mgr.check()
        return {"ok": True, "free": self._mgr.free_count(),
                "available": self._mgr.available()}

    def stats(self) -> dict:
        out = {"completed": self.completed,
               "active": sum(s is not None for s in self._slots),
               "waiting": self._waiting.qsize() + len(self._pending),
               "max_batch": self.max_batch,
               "max_len": self.max_len,
               "preemptions": self.preemptions,
               "prefill_tokens": self.prefill_tokens,
               "decode_tokens": self.decode_tokens,
               "prefix_cache": self._prefix_cache,
               "kv_preempt": self._preempt_on,
               "kv_exports": self.kv_exports,
               "kv_imports": self.kv_imports,
               "kv_grafts": self.kv_grafts,
               "graft_tokens": self.graft_tokens,
               "demote_published": self.demote_published,
               "demote_failures": self.demote_failures,
               "weight_version": self.weight_version,
               "weight_updates": self.weight_updates,
               "weight_syncs_skipped": self.weight_syncs_skipped,
               "last_weight_sync_ms": round(self.last_weight_sync_ms,
                                            3),
               # SLO loop inputs (serve/slo.py): recent-request latency
               # percentiles + the live sync window.
               "slo": self._slo_window.snapshot(),
               "sync_window": self._k_live,
               "sync_window_shrinks": self.sync_window_shrinks}
        if self._lora_banks is not None:
            with self._lora_lock:
                now = time.monotonic()
                out["lora"] = {
                    "slots": self.lora_slots,
                    "rank": self.lora_rank,
                    "free": len(self._lora_free),
                    "loads": self.adapter_loads,
                    "evictions": self.adapter_evictions,
                    # Residency export → replica_metrics → the handle's
                    # summary poll → kv_router.choose: the salt lets
                    # the router score salted prompt hashes per
                    # candidate; age drives its LRU reasoning.
                    "resident": {
                        mid: {"salt": m["salt"],
                              "version": m["version"],
                              "age": round(now - m["last_used"], 3)}
                        for mid, m in self._lora_meta.items()},
                }
        if self._mgr is not None:
            kv = self._mgr.stats()
            out["kv"] = kv
            out["prefix_hits"] = kv["hits"]
            out["prefix_misses"] = kv["misses"]
            out["prefix_hit_tokens"] = kv["hit_tokens"]
            out["evictions"] = kv["evictions"]
            out["cow_copies"] = kv["cow_copies"]
        self._flush_metrics(force=True)
        return out


class LLMServer:
    """Serve deployment body: one engine per replica.

    serve.deployment(LLMServer).options(...) — requests carry token-id
    prompts; a tokenizer front can be composed as another deployment.
    Engine memory knobs (page_size / kv_pages / prefix_cache /
    kv_preempt) are operator-tunable through `engine_config` in the
    declarative deploy config (serve/schema.py) and through
    `reconfigure` (user_config), which rebuilds the engine in place.

    **Pool roles** (disaggregated prefill/decode, DistServe/Mooncake
    shape): `role="prefill"` replicas run ONLY the prompt pass — the
    finished KV pages are sealed into an arena object and shipped to a
    replica of the `decode_deployment` pool, whose engine imports them
    (`kv_decode`) and owns the whole decode phase.  Prefill compute
    thus never steals decode batch slots, and the KV transfer rides the
    object plane (same-host moves take the direct-shm pull, cross-node
    the streaming-write path).  `decode_deployment` is the decode
    pool's deployment name (declarative config) or its bound
    Application/handle (Python composition).  Both pools should share
    the engine `seed` so a migrated continuation draws the same sample
    stream an unsplit engine would.  Kill switch RAY_TPU_PD_DISAGG=0
    (or per-request {"disagg": false}) serves unified on the prefill
    replica itself — same-run A/B.
    """

    # Adapter requests re-page + resubmit this many times total when a
    # concurrent tenant's load evicts their adapter between the
    # server's page-in and engine-loop admission (slots thrash when
    # adapters >> slots).  Admission precedes block/lane allocation and
    # the first token, so a resubmit is invisible to the client.
    _LORA_ADMIT_RETRIES = 3

    def __init__(self, model: str = "debug", *, max_batch: int = 8,
                 max_len: int | None = None, params=None, seed: int = 0,
                 warmup: bool = False, paged: bool = True,
                 page_size: int = 512, kv_pages: int | None = None,
                 prefix_cache: bool | None = None,
                 kv_preempt: bool | None = None,
                 steps_per_sync: int = 8,
                 role: str = "unified",
                 decode_deployment=None,
                 prefix_store: dict | None = None,
                 lora_slots: int = 0, lora_rank: int = 0,
                 lora_directory=None):
        from ray_tpu.models import llama

        _check_pool_role(role, decode_deployment)
        if role == "prefill" and not paged:
            raise ValueError(
                "role='prefill' requires a paged engine (KV migration "
                "is page-granular)")
        cfg = llama.llama_configs()[model] if isinstance(model, str) \
            else model
        name = "llm"
        self._app_name = None
        try:
            from ray_tpu.serve import replica as _replica

            ctx = _replica.get_current_context()
            if ctx is not None and ctx.deployment:
                name = ctx.deployment
                self._app_name = ctx.app_name
        except Exception:  # noqa: BLE001 - outside a replica
            pass
        self._engine_kwargs = dict(
            max_batch=max_batch, max_len=max_len, seed=seed, paged=paged,
            page_size=page_size, kv_pages=kv_pages,
            prefix_cache=prefix_cache, kv_preempt=kv_preempt,
            steps_per_sync=steps_per_sync, lora_slots=lora_slots,
            lora_rank=lora_rank, name=name)
        self._cfg = cfg
        self._params = params
        self._warmup = warmup
        self._role = role
        self._decode_dep = decode_deployment
        self._decode_handle = None
        self._decode_kv_handle = None
        # Migration observability (→ stats() → serve.replica_metrics):
        # bytes/ms through the object plane, split by side.  The pull
        # side mutates from the replica's thread POOL (kv_decode is a
        # sync method), so its counters take the lock; the put side
        # runs on the event loop and is naturally serialized.
        self._pd_lock = threading.Lock()
        self._migrations = 0
        self._pd_fallbacks = 0
        self._kv_migrate_bytes = 0
        self._kv_migrate_put_ms = 0.0
        self._kv_pull_bytes = 0
        self._kv_pull_ms = 0.0
        # Overload degradation ladder (serve/slo.py OverloadTracker,
        # pressure = engine queue depth): level 1 sheds PD-disagg to
        # unified serving (skip the migration round trips), level 2
        # also shrinks the decode sync window so queued requests admit
        # sooner.  Both restore on sustained recovery.  Kill switch
        # RAY_TPU_SERVE_DEGRADE=0.
        self._overload = slo.OverloadTracker(hi=max(4, 2 * max_batch))
        self._degraded_window = max(1, min(2, steps_per_sync))
        self._sheds = 0
        self._restores = 0
        # Tier-2 cluster prefix store (serve/prefix_store.py): the
        # client owns this replica's demoted arena objects and runs
        # the miss-path fetch/graft; config knobs ride the
        # `prefix_store` dict ({"enabled", "min_idle", "period_s",
        # "watermark_frac", "min_tokens", "migrate_ms", ...}).
        self._prefix_store_cfg = dict(prefix_store or {})
        self._prefix_client = None
        # Multi-LoRA page-in state (serve/lora.py): ONE in-flight load
        # per model_id (racing requests park on its future) + a short
        # TTL cache of the directory's (version) answer so the
        # resident-adapter fast path costs zero controller round
        # trips.  `lora_directory` injects an in-process
        # AdapterDirectory (tests / local mode).
        self._lora_client = None
        self._lora_directory = lora_directory
        self._lora_inflight: dict = {}
        self._lora_inflight_lock = threading.Lock()
        self._lora_seen: dict[str, tuple[float, int]] = {}
        self._lora_ttl = float(
            os.environ.get("RAY_TPU_LORA_TTL_S", "2.0") or 0.0)
        self.adapter_load_errors = 0
        self.adapter_admit_retries = 0
        self._closed = False
        self.engine = LLMEngine(cfg, params, **self._engine_kwargs)
        self._install_prefix_store()
        self.engine.start()
        if warmup:
            self.engine.warmup()

    def _install_prefix_store(self) -> None:
        """(Re)attach the prefix-store client + demotion hook to the
        current engine (constructor and every reconfigure rebuild).
        Disabled for dense engines, prefix_cache=0 engines, and
        explicitly via prefix_store={"enabled": False}."""
        from ray_tpu.serve import prefix_store as pstore

        if self._prefix_client is not None:
            self._prefix_client.close()
            self._prefix_client = None
        eng = self.engine
        cfg = self._prefix_store_cfg
        if (not eng.paged or eng._mgr is None
                or not eng._prefix_cache
                or cfg.get("enabled", True) is False):
            eng.set_prefix_store(None)
            return
        rid = None
        try:
            from ray_tpu.serve import replica as _replica

            ctx = _replica.get_current_context()
            if ctx is not None:
                rid = ctx.replica_tag or None
        except Exception:  # noqa: BLE001 - outside a replica
            pass
        self._prefix_client = pstore.PrefixStoreClient(
            app=self._app_name or "default", deployment=eng.name,
            # Unique in-process fallback: several servers can share one
            # interpreter (tests, local mode) and a bare pid would make
            # one server's close() withdraw its siblings' entries.
            replica_id=rid or f"pid:{os.getpid()}-{os.urandom(3).hex()}",
            seed=self._engine_kwargs.get("seed", 0), page=eng.page,
            config=cfg, directory=cfg.get("directory"))
        eng.set_prefix_store(
            self._prefix_client.publish,
            min_idle=cfg.get("min_idle", 256),
            period_s=cfg.get("period_s", 0.25),
            watermark_frac=cfg.get("watermark_frac", 0.125),
            limit=cfg.get("limit", 2),
            max_inflight=cfg.get("max_inflight", 2))

    # -------------------------------------------------- multi-LoRA
    def _request_model_id(self, request) -> str | None:
        """The request's adapter identity, gated PER REQUEST by the
        RAY_TPU_LORA kill switch (off → every request serves the base
        model — the same-run A/B arm).  Absent {"model_id": ...} =
        base model, always."""
        from ray_tpu.serve import kv_router

        if not isinstance(request, dict):
            return None
        mid = request.get("model_id")
        if mid is None or not kv_router.lora_on():
            return None
        return mid

    def _ensure_adapter_sync(self, model_id: str,
                             trace_ctx=None) -> None:
        """Make `model_id` device-resident before submit (blocking —
        callers keep it off the event loop).  Fast path: resident at
        the version the directory reported within the last
        RAY_TPU_LORA_TTL_S seconds — zero controller round trips.
        Slow path: ONE in-flight load per model_id (racing requests
        park on its future): directory lookup → object-plane pull
        (same-host direct-shm / cross-node streaming — the normal get
        path) → engine.load_adapter.  Every failure surfaces as a
        typed AdapterLoadError BEFORE the request holds a batch slot;
        the `serve.adapter_load` failpoint fires at entry, so an
        injected fault degrades to reject-early, never a wedged
        engine loop."""
        from ray_tpu import failpoints
        from ray_tpu.serve import lora as lora_mod

        eng = self.engine
        if failpoints.ACTIVE:
            try:
                failpoints.fire("serve.adapter_load")
            except BaseException as e:  # noqa: BLE001 - typed reject
                self.adapter_load_errors += 1
                raise AdapterLoadError(
                    f"adapter load faulted: {type(e).__name__}: {e}",
                    model_id=model_id, deployment=eng.name,
                    reason="load_failed") from e
        if eng._lora_banks is None:
            raise AdapterLoadError(
                "deployment has no adapter slots (set engine_config "
                "lora_slots)", model_id=model_id, deployment=eng.name,
                reason="lora_slots=0")
        now = time.monotonic()
        seen = self._lora_seen.get(model_id)
        if seen and seen[0] > now \
                and eng.adapter_resident(model_id, seen[1]):
            eng.adapter_touch(model_id)
            return
        with self._lora_inflight_lock:
            fut = self._lora_inflight.get(model_id)
            owner = fut is None
            if owner:
                fut = concurrent.futures.Future()
                self._lora_inflight[model_id] = fut
        if not owner:
            fut.result(timeout=120.0)   # re-raises the owner's error
            return
        try:
            t0 = time.time()
            try:
                if self._lora_client is None:
                    self._lora_client = lora_mod.LoraClient(
                        directory=self._lora_directory)
                entry = self._lora_client.lookup(model_id)
                if entry is None:
                    raise AdapterLoadError(
                        "no such adapter published",
                        model_id=model_id, deployment=eng.name,
                        reason="not_published")
                if not eng.adapter_resident(model_id,
                                            entry["version"]):
                    adapter = lora_mod.resolve_entry(entry)
                    eng.load_adapter(model_id, adapter,
                                     version=entry["version"])
                    if tracing.ENABLED:
                        tracing.emit(
                            "serve.adapter_load", t0, time.time(),
                            ctx=trace_ctx,
                            attrs={"model_id": model_id,
                                   "deployment": eng.name,
                                   "version": entry["version"],
                                   "bytes": entry.get("nbytes", 0)})
                eng.adapter_touch(model_id)
                self._lora_seen[model_id] = (
                    time.monotonic() + self._lora_ttl,
                    entry["version"])
                fut.set_result(None)
            except BaseException as e:  # noqa: BLE001 - typed reject
                self.adapter_load_errors += 1
                err = e if isinstance(e, AdapterLoadError) \
                    else AdapterLoadError(
                        f"adapter load faulted: "
                        f"{type(e).__name__}: {e}",
                        model_id=model_id, deployment=eng.name,
                        reason="load_failed")
                fut.set_exception(err)
                raise err from (None if e is err else e)
        finally:
            with self._lora_inflight_lock:
                self._lora_inflight.pop(model_id, None)

    def _graft_eligible(self, request) -> bool:
        """ONE copy of the miss-path gate for the unary and streaming
        entry points (they must never diverge): a store-capable
        request is a dict with a real token prompt of at least one
        page, not opted out per request, with the env switch on."""
        from ray_tpu.serve import prefix_store as pstore

        eng = self.engine
        if (self._prefix_client is None or not isinstance(request, dict)
                or not request.get("prefix_store", True)
                or eng._mgr is None or not eng._prefix_cache):
            return False
        prompt = request.get("prompt")
        if not isinstance(prompt, (list, tuple)) \
                or len(prompt) < eng.page:
            return False
        return pstore.prefix_store_on()

    def _maybe_graft_sync(self, request: dict) -> None:
        """Miss-path store consultation for one request (the tentpole
        leg; blocking — callers keep it off the event loop): compare
        the local radix match with the cluster directory and graft the
        deepest affordable stored prefix before submitting.
        Per-request kill switches: RAY_TPU_PREFIX_STORE=0 and
        {"prefix_store": false}.  Any failure degrades to a plain
        local prefill."""
        if not self._graft_eligible(request):
            return
        try:
            # Adapter requests graft under the adapter's salt: a tier-2
            # entry only matches KV computed by the SAME (adapter,
            # version) — the base model's cache and every other
            # adapter's hash to disjoint keys.
            mid = self._request_model_id(request)
            salt = self.engine.adapter_salt_of(mid) if mid else 0
            self._prefix_client.maybe_graft(
                self.engine, list(request["prompt"]), salt=salt)
        except Exception:  # noqa: BLE001 - degrade, never fail
            pass

    async def _maybe_graft_async(self, request: dict) -> None:
        import asyncio

        if not self._graft_eligible(request):
            return
        await asyncio.get_running_loop().run_in_executor(
            None, self._maybe_graft_sync, request)

    # ----------------------------------------------- overload ladder
    def _update_pressure(self) -> int:
        """Feed the engine's queue depth to the hysteresis tracker; on
        a level change apply/restore the sync-window knob and emit a
        flight-recorder span so a trace shows WHY service degraded.
        Kill switch RAY_TPU_SERVE_DEGRADE=0 pins level 0 (restoring a
        previously-shrunk window)."""
        eng = self.engine
        if not slo.degrade_on():
            if self._overload.level:
                self._overload.level = 0
                eng.set_sync_window(None)
            return 0
        depth = eng._waiting.qsize() + len(eng._pending)
        level, prev = self._overload.update(depth)
        if level != prev:
            eng.set_sync_window(
                self._degraded_window if level >= 2 else None)
            if level > prev:
                self._sheds += 1
            else:
                self._restores += 1
            if tracing.ENABLED:
                tracing.emit(
                    "serve.shed" if level > prev else "serve.restore",
                    time.time(),
                    attrs={"deployment": eng.name, "level": level,
                           "from": prev, "depth": depth,
                           "sync_window": eng._k_live})
        return level

    # ------------------------------------------------- prefill/decode
    def _disagg(self, request: dict) -> bool:
        from ray_tpu.serve import kv_router

        return (self._role == "prefill"
                and self._decode_dep is not None
                and self.engine.paged
                and kv_router.pd_disagg_on()
                and request.get("disagg", True)
                and request.get("max_new_tokens", 32) > 1)

    def _get_decode_handle(self):
        """The decode pool's handle pair, created once per server: the
        base handle (full-generate fallback) and its kv_decode-bound
        sibling (a .options() handle owns its own membership cache and
        router thread — per-request construction would cost a
        controller RT every call)."""
        if self._decode_handle is None:
            dd = self._decode_dep
            if isinstance(dd, str):
                from ray_tpu import serve as serve_api

                base = serve_api.get_deployment_handle(
                    dd, self._app_name or "default")
            else:
                # Bound composition: serve.run already substituted the
                # child Application with a DeploymentHandle.
                base = dd
            self._decode_kv_handle = base.options(
                method_name="kv_decode")
            self._decode_handle = base
        return self._decode_handle

    async def _local_generate(self, request: dict, t_start: float,
                              why: str) -> dict:
        import asyncio

        fut = self.engine.submit(
            request["prompt"],
            max_new_tokens=request.get("max_new_tokens", 32),
            temperature=request.get("temperature", 0.0),
            eos_id=request.get("eos_id"))
        out = await asyncio.wrap_future(fut)
        out["total_s"] = time.perf_counter() - t_start
        out["pd_fallback"] = why
        return out

    async def _prefill_decode(self, request: dict) -> dict:
        """The migration path: prefill here, seal the KV pages into an
        arena object, hand the refs to a decode replica.  Failure at
        any stage degrades, never fails the request: export error →
        serve unified locally; decode-pool error (a replica dying
        mid-migration, an import fault) → full re-prefill on a
        surviving decode replica, then locally as the last resort."""
        import asyncio

        import ray_tpu

        t_start = time.perf_counter()
        try:
            pre = await asyncio.wrap_future(self.engine.submit(
                request["prompt"], max_new_tokens=1,
                temperature=request.get("temperature", 0.0),
                eos_id=request.get("eos_id"), prefill_only=True))
        except Exception:  # noqa: BLE001 - export window faults
            self._pd_fallbacks += 1
            return await self._local_generate(request, t_start,
                                              "export_failed")
        exp = pre.get("kv_export")
        eos = request.get("eos_id")
        if exp is None or (eos is not None and pre["tokens"]
                           and pre["tokens"][-1] == eos):
            return {"tokens": pre["tokens"], "ttft_s": pre["ttft_s"],
                    "total_s": time.perf_counter() - t_start}
        loop = asyncio.get_running_loop()
        # Executor threads don't inherit the handler task's contextvars:
        # carry the request's trace into the put explicitly.
        trace_ctx = tracing.capture() if tracing.ENABLED else None

        def _put():
            t0 = time.perf_counter()
            with tracing.span("serve.kv_put", ctx=trace_ctx,
                              attrs={"bytes": exp["kv"].nbytes}), \
                    memledger.tag("kv_export",
                                  label="serve/llm.py kv_export"):
                r = ray_tpu.put(exp["kv"])
            return r, (time.perf_counter() - t0) * 1000.0

        # put() may block on arena allocation — keep it off the event
        # loop (same rule as every blocking framework call here).
        ref, put_ms = await loop.run_in_executor(None, _put)
        self._migrations += 1
        self._kv_migrate_bytes += exp["kv"].nbytes
        self._kv_migrate_put_ms += put_ms
        meta = {"prompt": list(request["prompt"]),
                "tokens": exp["tokens"], "kv_len": exp["len"],
                "page": exp["page"], "sample_seed": exp["sample_seed"],
                "max_new_tokens": request.get("max_new_tokens", 32),
                "temperature": request.get("temperature", 0.0),
                "eos_id": eos}
        # The arena now holds the KV; drop the host copy BEFORE the
        # decode await (seconds per request) or every in-flight
        # migration carries its prompt KV twice.
        pre.pop("kv_export", None)
        exp = None
        handle = self._get_decode_handle()
        try:
            out = await self._decode_kv_handle.remote(meta, ref)
            return {"tokens": out["tokens"], "ttft_s": pre["ttft_s"],
                    "total_s": time.perf_counter() - t_start,
                    "disagg": True}
        except Exception:  # noqa: BLE001 - decode pool failed
            self._pd_fallbacks += 1
            del ref            # free the orphaned KV object
            try:
                out = await handle.remote({**request, "disagg": False})
                out["pd_fallback"] = "full_reprefill"
                return out
            except Exception:  # noqa: BLE001 - decode pool gone
                return await self._local_generate(request, t_start,
                                                  "local")

    def kv_decode(self, meta: dict, kv_ref) -> dict:
        """Decode-pool entry point: pull the migrated KV object (the
        ref arrives nested in the request args, so the pull happens
        HERE — same-host via the direct-shm/arena-view path, cross-node
        via chunked streaming), import it into this engine's pool, and
        run the decode phase to completion."""
        import ray_tpu
        from ray_tpu.object_ref import ObjectRef

        t0 = time.perf_counter()
        with tracing.span("serve.kv_pull") as sp:
            blob = kv_ref
            if isinstance(blob, ObjectRef):
                blob = ray_tpu.get(blob)
            blob = np.asarray(blob)
            sp["bytes"] = blob.nbytes
        pull_ms = (time.perf_counter() - t0) * 1000.0
        fut = self.engine.kv_import(
            meta["prompt"], meta["tokens"], blob,
            kv_len=meta["kv_len"],
            max_new_tokens=meta.get("max_new_tokens", 32),
            temperature=meta.get("temperature", 0.0),
            eos_id=meta.get("eos_id"),
            sample_seed=meta.get("sample_seed", 0))
        with self._pd_lock:
            self._kv_pull_bytes += blob.nbytes
            self._kv_pull_ms += pull_ms
        del blob, kv_ref       # the engine holds the view until scatter
        out = fut.result()
        out["migrated"] = True
        return out

    def update_weights(self, refs, version: int | None = None) -> int:
        """Replica-side weight push (online RLHF): stage a fresh param
        tree on this replica's engine — decode keeps running; the swap
        lands between sync windows.  `refs` resolves exactly as
        LLMEngine.update_weights documents (tree / ObjectRef / list of
        refs).  Returns the staged (or, kill-switched, current)
        version."""
        v = self.engine.update_weights(refs, version)
        if self._prefix_client is not None:
            # Cached KV belongs to the policy that computed it — the
            # engine flushes tier 1; tier 2 invalidates here (lookup's
            # version filter already refuses stale entries, this
            # reclaims their arena bytes too).
            try:
                self._prefix_client.invalidate(v)
            except Exception:  # noqa: BLE001 - store is best-effort
                pass
        return v

    def kv_check(self) -> dict:
        """Assert the engine's block-state partition (test/ops probe):
        raises if any block is leaked or double-booked.  Also reports
        the tier-2 prefix objects this replica still owns, and — after
        shutdown — asserts that count is ZERO (demoted subtrees must
        be freed when the app is deleted)."""
        out = self.engine.kv_check()
        if self._prefix_client is not None:
            n = self._prefix_client.object_count()
            out["prefix_store_objects"] = n
            if self._closed and n:
                raise AssertionError(
                    f"{n} tier-2 prefix arena objects leaked after "
                    "shutdown (demoted subtrees must die with the app)")
        return out

    async def __call__(self, request: dict) -> dict:
        import asyncio

        # Degradation ladder: under sustained overload (level >= 1)
        # disaggregation SHEDS to unified serving on this replica —
        # same engine, same seed, token-identical output, minus the
        # migration round trips the overloaded pool can't afford.
        level = self._update_pressure()
        model_id = self._request_model_id(request)
        attempts = self._LORA_ADMIT_RETRIES if model_id is not None else 1
        for attempt in range(attempts):
            if model_id is not None:
                # Adapter page-in BEFORE the graft lookup: the radix /
                # store keys are salted per (adapter, version), and the
                # salt is only known once the directory's version is.
                await asyncio.get_running_loop().run_in_executor(
                    None, self._ensure_adapter_sync, model_id,
                    tracing.current())
            if level < 1 and attempt == 0:
                # Overloaded replicas (level >= 1) skip the store
                # entirely: a migration's extra bytes/RTs are exactly
                # what a drowning pool can't afford — the
                # degradation-ladder discipline.
                await self._maybe_graft_async(request)
            # Adapter requests serve unified: the KV export/import leg
            # would also have to ship adapter identity and the decode
            # pool re-page the weights — cost without benefit at LoRA
            # sizes.
            if level < 1 and model_id is None and self._disagg(request):
                return await self._prefill_decode(request)
            fut = self.engine.submit(
                request["prompt"],
                max_new_tokens=request.get("max_new_tokens", 32),
                temperature=request.get("temperature", 0.0),
                eos_id=request.get("eos_id"),
                model_id=model_id)
            try:
                return await asyncio.wrap_future(fut)
            except AdapterLoadError as e:
                if e.reason != "not_resident" or attempt >= attempts - 1:
                    raise
                # Evicted between page-in and admission by a concurrent
                # tenant's load (slots thrash when adapters >> slots).
                # The request held no blocks or lanes yet — admission
                # failed before any — so re-page and resubmit.
                self._lora_seen.pop(model_id, None)
                self.adapter_admit_retries += 1

    def stream(self, request: dict):
        """Token-streaming generator: yields each token id as the engine
        decodes it.  Consumed via handle.options(stream=True).remote(...)
        or the HTTP proxy's chunked path (x-serve-stream: 1)."""
        if isinstance(request, dict) and "prompt" not in request:
            request = request.get("body") or request
        # The ladder must track streaming traffic too: without this a
        # streaming-only workload could neither enter overload nor
        # restore a previously-shrunk sync window.
        level = self._update_pressure()
        model_id = self._request_model_id(request)
        attempts = self._LORA_ADMIT_RETRIES if model_id is not None else 1
        for attempt in range(attempts):
            if model_id is not None:
                # stream() runs on a pool thread — blocking is fine.
                self._ensure_adapter_sync(model_id, tracing.current())
            if level < 1 and attempt == 0:
                self._maybe_graft_sync(request)
            q: queue.Queue = queue.Queue()
            fut = self.engine.submit(
                request["prompt"],
                max_new_tokens=request.get("max_new_tokens", 32),
                temperature=request.get("temperature", 0.0),
                eos_id=request.get("eos_id"),
                token_queue=q,
                model_id=model_id)
            while True:
                tok = q.get()
                if tok is None:
                    break
                yield tok
            # The None sentinel is emitted just BEFORE the future
            # resolves; wait briefly so an engine failure can't silently
            # truncate the stream as a clean-looking completion.
            try:
                exc = fut.exception(timeout=5.0)
            except concurrent.futures.TimeoutError:
                exc = None
            if exc is None:
                return
            if (isinstance(exc, AdapterLoadError)
                    and exc.reason == "not_resident"
                    and attempt < attempts - 1):
                # Admission-time eviction race (see __call__): nothing
                # was streamed — admission precedes the first token —
                # so a re-paged resubmit is transparent to the consumer.
                self._lora_seen.pop(model_id, None)
                self.adapter_admit_retries += 1
                continue
            raise exc

    def stats(self) -> dict:
        out = self.engine.stats()
        out["pd"] = {
            "role": self._role,
            "migrations": self._migrations,
            "fallbacks": self._pd_fallbacks,
            "kv_migrate_bytes": self._kv_migrate_bytes,
            "kv_migrate_put_ms": round(self._kv_migrate_put_ms, 3),
            "kv_pull_bytes": self._kv_pull_bytes,
            "kv_pull_ms": round(self._kv_pull_ms, 3),
        }
        out["overload"] = {
            "level": self._overload.level,
            "sheds": self._sheds,
            "restores": self._restores,
        }
        out["prefix_store"] = (self._prefix_client.stats()
                               if self._prefix_client is not None
                               else {"enabled": False})
        if "lora" in out:
            out["lora"]["load_errors"] = self.adapter_load_errors
            out["lora"]["admit_retries"] = self.adapter_admit_retries
        return out

    def reconfigure(self, user_config: dict) -> None:
        """Apply engine knobs from a declarative config without a code
        change (serve/schema.py engine_config or user_config; the same
        key set, including the operator-facing `kv_blocks` name).
        Knobs that reshape device memory rebuild the engine; the old
        engine's thread is stopped FIRST (deterministic teardown, not
        GC) and any requests it still held fail with a clear error —
        the controller applies config-only changes without draining, so
        a silent stop would hang those futures forever."""
        if not user_config:
            return
        from ray_tpu.serve.schema import ENGINE_CONFIG_KEYS

        allowed = ENGINE_CONFIG_KEYS | {"kv_pages", "paged"}
        unknown = set(user_config) - allowed
        if unknown:
            raise ValueError(
                f"unknown engine_config keys {sorted(unknown)}; "
                f"valid: {sorted(allowed)}")
        cfg = dict(user_config)
        ps_given = cfg.pop("prefix_store", None)
        # Pool-role knobs live on the SERVER, not the engine: applying
        # them never costs an engine rebuild.  Validate the WHOLE new
        # configuration before mutating anything — a rejected
        # reconfigure must leave the server exactly as it was.
        new_role = cfg.pop("role", None) or self._role
        dd_given = cfg.pop("decode_deployment", None)
        new_dd = self._decode_dep if dd_given is None else dd_given
        if new_role != "prefill" and dd_given is None:
            # Moving away from prefill sheds an inherited decode
            # target (there is no explicit clear syntax); an EXPLICIT
            # target with a non-prefill role is still rejected below.
            new_dd = None
        _check_pool_role(new_role, new_dd)
        if "kv_blocks" in cfg:
            cfg["kv_pages"] = cfg.pop("kv_blocks")
        kwargs = {**self._engine_kwargs, **cfg}
        if new_role == "prefill" and not kwargs.get("paged", True):
            # Mirror the constructor's check: this combination must
            # fail at (re)configuration, not silently serve unified.
            raise ValueError(
                "role='prefill' requires a paged engine (KV migration "
                "is page-granular)")
        def commit_roles():
            self._role = new_role
            if new_dd is not self._decode_dep:
                self._decode_dep = new_dd
                self._decode_handle = None
                self._decode_kv_handle = None

        if kwargs == self._engine_kwargs:
            commit_roles()
            if ps_given is not None:
                self._prefix_store_cfg = dict(ps_given)
                self._install_prefix_store()
            return
        old = self.engine
        old.stop()
        old.abort_pending(RuntimeError(
            "LLM engine rebuilt by reconfigure; resubmit the request"))
        self._engine_kwargs = kwargs
        # Role/handle state commits only once the rebuild succeeded: a
        # constructor failure must not leave a half-applied role on top
        # of the (unavoidably) stopped engine.
        self.engine = LLMEngine(self._cfg, self._params, **kwargs)
        # The fresh engine's banks are empty: drop the residency TTL
        # cache so the next adapter request re-pages rather than
        # trusting a stale "resident" answer.
        self._lora_seen.clear()
        commit_roles()
        if ps_given is not None:
            self._prefix_store_cfg = dict(ps_given)
        # The rebuilt engine needs the demotion hook re-attached (and
        # the old engine's published entries withdrawn — their KV may
        # no longer match the new memory shape).
        self._install_prefix_store()
        self.engine.start()
        if self._warmup:
            self.engine.warmup()

    def shutdown(self) -> None:
        """Explicit close hook: Replica.prepare_for_shutdown calls this
        on teardown/drain (serve reconfigure, rolling update, app
        delete), so the engine thread stops deterministically instead
        of at GC time.  Replica drain waits out in-flight requests
        first; anything still queued fails instead of hanging."""
        self.engine.stop()
        self.engine.abort_pending(
            RuntimeError("LLM engine shut down with the replica"))
        # AFTER engine.stop(): the export thread drains in-flight
        # demotions first, so a publish can't race the withdraw and
        # strand an arena object past app delete.
        self._closed = True
        if self._prefix_client is not None:
            try:
                self._prefix_client.close()
            except Exception:  # noqa: BLE001 - controller already gone
                pass

    def __del__(self):
        # GC backstop only — the deterministic path is shutdown().
        try:
            self.engine.stop()
        except Exception:  # noqa: BLE001
            pass
