"""@serve.multiplexed: per-replica LRU cache of loaded models.

Analog of ray: python/ray/serve/multiplex.py (_ModelMultiplexWrapper).
A replica serving many fine-tuned variants keeps up to
`max_num_models_per_replica` loaded, evicting least-recently-used (on TPU:
evicting frees HBM for the incoming model's weights).

Two disciplines the naive version got wrong:

  - Eviction calls the model's EXPLICIT resource hooks — ``close()``,
    else ``shutdown()`` — before dropping the reference.  A model
    holding device memory or worker processes must not wait on GC
    (``__del__`` still runs when the reference dies, as a backstop).
  - Loads run OUTSIDE the state lock.  A model load is seconds of
    checkpoint IO; serializing every request of a replica behind one
    load stalls traffic for models that are already resident.  Racing
    requests for the SAME model coalesce on one pending future;
    requests for resident models proceed immediately.

The replica's metrics report resident model ids (`resident_models`)
through `get_metrics()["multiplexed"]`; the handle's summary poll feeds
them to kv_router.choose, which routes a multiplexed request to a
replica that already holds its model (see serve/lora.py for the
LLM-engine flavor of the same idea).
"""
from __future__ import annotations

import asyncio
import collections
import contextvars
import functools
import inspect

_STATE_PREFIX = "__serve_multiplex_"


async def _close_model(model) -> None:
    """Release a model's resources deterministically: the first of
    close() / shutdown() that exists, awaited if async.  Errors are
    swallowed — eviction must never fail the request that triggered
    it."""
    for name in ("close", "shutdown"):
        fn = getattr(model, name, None)
        if callable(fn):
            try:
                r = fn()
                if inspect.isawaitable(r):
                    await r
            except Exception:  # noqa: BLE001 - eviction never fails
                pass
            return
    # No explicit hook: legacy models relied on eager finalization at
    # eviction time (GC order under test is not deterministic).
    del_fn = getattr(model, "__del__", None)
    if del_fn is not None:
        try:
            del_fn()
        except Exception:  # noqa: BLE001
            pass


def multiplexed(func=None, *, max_num_models_per_replica: int = 3):
    def wrap(f):
        attr = _STATE_PREFIX + f.__name__

        @functools.wraps(f)
        async def wrapper(self, model_id: str):
            # Indirect through the module-level setter: a direct global
            # reference to the ContextVar would be captured by value when
            # cloudpickle ships the decorated class (unpicklable).
            _set_current_model_id(model_id)
            state = getattr(self, attr, None)
            if state is None:
                state = {"models": collections.OrderedDict(),
                         "lock": asyncio.Lock(), "pending": {}}
                setattr(self, attr, state)
            models = state["models"]
            victims = []
            async with state["lock"]:
                if model_id in models:
                    models.move_to_end(model_id)
                    return models[model_id]
                fut = state["pending"].get(model_id)
                if fut is None:
                    owner = True
                    fut = asyncio.get_running_loop().create_future()
                    state["pending"][model_id] = fut
                    # Reserve capacity BEFORE loading (evicting frees
                    # the memory the incoming model needs): in-flight
                    # loads count against the cap too.
                    room = max(1, max_num_models_per_replica)
                    while models and \
                            len(models) + len(state["pending"]) > room:
                        victims.append(models.popitem(last=False)[1])
                else:
                    owner = False
            if not owner:
                # Coalesce on the in-flight load (its owner's failure
                # re-raises here; a retry is a fresh request).
                return await fut
            try:
                for m in victims:
                    await _close_model(m)
                loaded = f(self, model_id)
                if inspect.isawaitable(loaded):
                    loaded = await loaded
            except BaseException as e:
                async with state["lock"]:
                    state["pending"].pop(model_id, None)
                if not fut.done():
                    fut.set_exception(e)
                    fut.exception()   # owner re-raises; mark retrieved
                raise
            async with state["lock"]:
                state["pending"].pop(model_id, None)
                models[model_id] = loaded
            if not fut.done():
                fut.set_result(loaded)
            return loaded
        return wrapper

    if func is not None:
        return wrap(func)
    return wrap


def resident_models(instance) -> list[str]:
    """Model ids currently loaded by any @serve.multiplexed method of
    `instance` (resident only — in-flight loads don't count until they
    commit).  The replica exports this through get_metrics; the handle
    routes on it."""
    out: list[str] = []
    try:
        attrs = vars(instance)
    except TypeError:
        return out
    for name, state in attrs.items():
        if name.startswith(_STATE_PREFIX) and isinstance(state, dict) \
                and isinstance(state.get("models"), dict):
            out.extend(state["models"].keys())
    return out


def get_multiplexed_model_id() -> str:
    """Inside a multiplexed request, the requested model id (ray:
    serve.get_multiplexed_model_id)."""
    return _current_model_id.get("")


def _set_current_model_id(model_id: str) -> None:
    _current_model_id.set(model_id)


_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")
