"""@serve.multiplexed: per-replica LRU cache of loaded models.

Analog of ray: python/ray/serve/multiplex.py (_ModelMultiplexWrapper).
A replica serving many fine-tuned variants keeps up to
`max_num_models_per_replica` loaded, evicting least-recently-used (on TPU:
evicting frees HBM for the incoming model's weights).
"""
from __future__ import annotations

import asyncio
import collections
import functools
import inspect


def multiplexed(func=None, *, max_num_models_per_replica: int = 3):
    def wrap(f):
        attr = f"__serve_multiplex_{f.__name__}"

        @functools.wraps(f)
        async def wrapper(self, model_id: str):
            # Indirect through the module-level setter: a direct global
            # reference to the ContextVar would be captured by value when
            # cloudpickle ships the decorated class (unpicklable).
            _set_current_model_id(model_id)
            state = getattr(self, attr, None)
            if state is None:
                state = {"models": collections.OrderedDict(),
                         "lock": asyncio.Lock()}
                setattr(self, attr, state)
            models = state["models"]
            async with state["lock"]:
                if model_id in models:
                    models.move_to_end(model_id)
                    return models[model_id]
                while len(models) >= max_num_models_per_replica:
                    _mid, evicted = models.popitem(last=False)
                    del_fn = getattr(evicted, "__del__", None)
                    if del_fn is not None:
                        try:
                            del_fn()
                        except Exception:  # noqa: BLE001
                            pass
                loaded = f(self, model_id)
                if inspect.isawaitable(loaded):
                    loaded = await loaded
                models[model_id] = loaded
                return loaded
        return wrapper

    if func is not None:
        return wrap(func)
    return wrap


def get_multiplexed_model_id() -> str:
    """Inside a multiplexed request, the requested model id (ray:
    serve.get_multiplexed_model_id)."""
    return _current_model_id.get("")


def _set_current_model_id(model_id: str) -> None:
    _current_model_id.set(model_id)


import contextvars  # noqa: E402

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")
