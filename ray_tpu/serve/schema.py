"""Declarative Serve application config (serve build / serve deploy).

Analog of ray: python/ray/serve/schema.py (ServeDeploySchema /
ServeApplicationSchema / DeploymentSchema) — the config-as-data path: an
application is described by an import path plus per-deployment overrides,
applied idempotently via REST or `serve deploy`, instead of a Python
driver calling serve.run.
"""
from __future__ import annotations

import dataclasses
import importlib
from typing import Any

from ray_tpu.serve.config import autoscaling_config_from_dict
from ray_tpu.serve.deployment import Application


# LLM engine memory knobs an operator may set per deployment in the
# declarative config, without code changes (they land in the servable's
# init kwargs — see LLMServer).  kv_blocks is the operator-facing name
# for the page-pool size (engine kwarg kv_pages).  role /
# decode_deployment split an app's replicas into disaggregated
# prefill/decode pools (see LLMServer pool roles).  lora_slots /
# lora_rank size the engine's multi-LoRA adapter banks (serve/lora.py;
# 0 = dense-only — the static bucket every adapter must fit, per the
# one-jitted-program invariant).
ENGINE_CONFIG_KEYS = {"page_size", "kv_blocks", "prefix_cache",
                      "kv_preempt", "max_batch", "max_len",
                      "steps_per_sync", "role", "decode_deployment",
                      "prefix_store", "lora_slots", "lora_rank"}

ENGINE_ROLES = ("unified", "prefill", "decode")

# The LLMEngine's default page size: pool page_size declarations are
# compared against it when one side of a prefill→decode edge omits the
# knob (see _validate_pool_roles).
_DEFAULT_PAGE_SIZE = 512


@dataclasses.dataclass
class DeploymentSchema:
    """Per-deployment override block (ray: DeploymentSchema)."""

    name: str
    num_replicas: int | str | None = None
    max_ongoing_requests: int | None = None
    user_config: Any = None
    autoscaling_config: dict | None = None
    ray_actor_options: dict | None = None
    # Replica admission-queue bound (serve/replica.py early rejection);
    # -1 = 2 x max_ongoing_requests, 0 = no queue.
    max_queued_requests: int | None = None
    # KV-cache / batching knobs for LLM deployments (serve/llm.py):
    # merged into the deployment's init kwargs at apply time.
    engine_config: dict | None = None

    @classmethod
    def from_dict(cls, d: dict) -> "DeploymentSchema":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown deployment config keys {unknown}")
        ac = d.get("autoscaling_config")
        if ac is not None:
            # Deploy-time validation with field-naming errors (unknown
            # keys, min>max, non-positive targets) — the raw dict used
            # to pass straight through and fail deep inside the
            # controller's first scaling decision.
            if not isinstance(ac, dict):
                raise ValueError(
                    f"deployment {d.get('name')!r}: autoscaling_config "
                    f"must be a dict, got {type(ac).__name__}")
            autoscaling_config_from_dict(
                ac, where=f"deployment {d.get('name')!r} "
                          f"autoscaling_config")
        mq = d.get("max_queued_requests")
        if mq is not None and (not isinstance(mq, int)
                               or isinstance(mq, bool) or mq < -1):
            raise ValueError(
                f"deployment {d.get('name')!r}: max_queued_requests "
                f"must be an int >= -1 (-1 = default bound, 0 = no "
                f"queue), got {mq!r}")
        ec = d.get("engine_config")
        if ec is not None:
            bad = set(ec) - ENGINE_CONFIG_KEYS
            if bad:
                raise ValueError(
                    f"unknown engine_config keys {sorted(bad)}; valid: "
                    f"{sorted(ENGINE_CONFIG_KEYS)}")
            role = ec.get("role")
            if role is not None and role not in ENGINE_ROLES:
                raise ValueError(
                    f"deployment {d.get('name')!r}: engine_config.role "
                    f"must be one of {list(ENGINE_ROLES)}, got {role!r}")
            ps = ec.get("prefix_store")
            if ps is not None and not isinstance(ps, dict):
                raise ValueError(
                    f"deployment {d.get('name')!r}: "
                    f"engine_config.prefix_store must be a dict of "
                    f"tier-2 store knobs (enabled/min_idle/period_s/"
                    f"watermark_frac/...), got {type(ps).__name__}")
            dd = ec.get("decode_deployment")
            if dd is not None and not isinstance(dd, str):
                raise ValueError(
                    f"deployment {d.get('name')!r}: "
                    f"engine_config.decode_deployment must be a "
                    f"deployment name, got {type(dd).__name__}")
            if dd is not None and role != "prefill":
                # Covers role omitted too: a dangling decode target
                # would otherwise deploy cleanly and serve unified
                # forever with no migration and no error.
                raise ValueError(
                    f"deployment {d.get('name')!r}: "
                    f"decode_deployment only applies to role='prefill' "
                    f"(got role={role!r})")
            nr = d.get("num_replicas")
            if role in ("prefill", "decode") and isinstance(nr, int) \
                    and nr < 1:
                raise ValueError(
                    f"deployment {d.get('name')!r}: a {role!r} pool "
                    f"needs num_replicas >= 1, got {nr} (a zero-sized "
                    f"pool cannot serve its phase)")
        return cls(**d)


def _validate_pool_roles(app_name, deps: "list[DeploymentSchema]"):
    """Cross-deployment pool-role checks (the per-deployment value
    checks live in DeploymentSchema.from_dict).  A prefill pool must
    name a decode pool it ships KV to, and when that pool is declared
    in the same config its role must actually be 'decode' — the
    classic misconfigurations fail at validation, not at first
    request."""
    roles = {}
    pages = {}
    for dep in deps:
        ec = dep.engine_config or {}
        roles[dep.name] = (ec.get("role"), ec.get("decode_deployment"))
        if "page_size" in ec:
            pages[dep.name] = ec["page_size"]
    for name, (role, dd) in roles.items():
        if role != "prefill":
            continue
        if dd is None:
            raise ValueError(
                f"app {app_name!r}: deployment {name!r} declares "
                f"role='prefill' but no decode_deployment — a prefill "
                f"pool with no decode pool cannot serve")
        if dd == name:
            raise ValueError(
                f"app {app_name!r}: deployment {name!r} names itself "
                f"as its decode_deployment")
        if dd in roles and roles[dd][0] != "decode":
            raise ValueError(
                f"app {app_name!r}: deployment {name!r} routes decode "
                f"to {dd!r}, whose role is "
                f"{roles[dd][0] or 'unified'!r} (must be 'decode')")
        if pages.get(name, _DEFAULT_PAGE_SIZE) != \
                pages.get(dd, _DEFAULT_PAGE_SIZE):
            # A page-size mismatch breaks the migrated-KV shape on
            # EVERY request (import fails → permanent full-re-prefill
            # fallback) — fail it here, not at first request.  A side
            # that omits page_size is compared at the engine default,
            # so declaring it on only one pool is caught too.
            raise ValueError(
                f"app {app_name!r}: prefill pool {name!r} "
                f"(page_size={pages.get(name, _DEFAULT_PAGE_SIZE)}) "
                f"and decode pool {dd!r} "
                f"(page_size={pages.get(dd, _DEFAULT_PAGE_SIZE)}) "
                f"must agree on page_size — migrated KV pages are "
                f"page-granular (declare it on both or neither)")


@dataclasses.dataclass
class ApplicationSchema:
    """One application (ray: ServeApplicationSchema)."""

    name: str
    import_path: str                      # "module.sub:app_or_builder"
    route_prefix: str = "/"
    args: dict = dataclasses.field(default_factory=dict)
    deployments: list[DeploymentSchema] = dataclasses.field(
        default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "ApplicationSchema":
        d = dict(d)
        deps = [DeploymentSchema.from_dict(x)
                for x in d.pop("deployments", [])]
        known = {f.name for f in dataclasses.fields(cls)} - {"deployments"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown application config keys {unknown}")
        _validate_pool_roles(d.get("name"), deps)
        return cls(deployments=deps, **d)

    def load(self) -> Application:
        """Resolve import_path to a bound Application and apply the
        per-deployment overrides (ray: build_app + override_deployment).

        The graph is COPIED before overriding: a module-level Application
        is cached in sys.modules, and mutating it in place would leak
        overrides across applies (and across apps sharing an
        import_path)."""
        mod_name, _, attr = self.import_path.partition(":")
        if not attr:
            raise ValueError(
                f"import_path {self.import_path!r} must be 'module:attr'")
        target = getattr(importlib.import_module(mod_name), attr)
        if callable(target) and not isinstance(target, Application):
            target = target(**self.args)   # app builder function
        if not isinstance(target, Application):
            raise TypeError(
                f"{self.import_path} resolved to {type(target).__name__}, "
                "expected a bound Application (Deployment.bind())")
        target = _copy_app(target, {})
        overrides = {d.name: d for d in self.deployments}
        for node in target._walk({}):
            ov = overrides.pop(node.deployment.name, None)
            if ov is None:
                continue
            opts = {k: v for k, v in dataclasses.asdict(ov).items()
                    if k not in ("name", "engine_config")
                    and v is not None}
            if opts:
                node.deployment = node.deployment.options(**opts)
            if ov.engine_config:
                # Operator-tunable engine memory: kv_blocks is the
                # config-facing name for the engine's kv_pages kwarg.
                ec = dict(ov.engine_config)
                if "kv_blocks" in ec:
                    ec["kv_pages"] = ec.pop("kv_blocks")
                node.init_kwargs = {**node.init_kwargs, **ec}
        if overrides:
            raise ValueError(
                f"config overrides for unknown deployments: "
                f"{sorted(overrides)}")
        return target


def _copy_app(node: Application, memo: dict) -> Application:
    """Structural copy of an Application graph (deployment objects are
    shared — node.deployment is REPLACED, never mutated, on override)."""
    if id(node) in memo:
        return memo[id(node)]

    def sub(v):
        return _copy_app(v, memo) if isinstance(v, Application) else v

    new = Application(node.deployment,
                      tuple(sub(a) for a in node.init_args),
                      {k: sub(v) for k, v in node.init_kwargs.items()})
    memo[id(node)] = new
    return new


@dataclasses.dataclass
class DeploySchema:
    """Top-level multi-app config (ray: ServeDeploySchema — the payload
    of `serve deploy` / PUT /api/serve/applications)."""

    applications: list[ApplicationSchema]
    http_options: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "DeploySchema":
        apps = [ApplicationSchema.from_dict(a)
                for a in d.get("applications", [])]
        names = [a.name for a in apps]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate application names in {names}")
        prefixes = [a.route_prefix for a in apps]
        if len(prefixes) != len(set(prefixes)):
            raise ValueError(f"duplicate route prefixes in {prefixes}")
        return cls(applications=apps,
                   http_options=d.get("http_options", {}))


def apply_config(config: dict) -> dict:
    """Deploy a declarative config (idempotent; ray: serve deploy).

    Returns {app_name: route_prefix}.  Apps present in the running serve
    instance but absent from the config are DELETED (declarative
    semantics, ray: ServeDeploySchema apply)."""
    from ray_tpu import serve

    schema = DeploySchema.from_dict(config)
    serve.start(http_options=schema.http_options or None)
    desired = {}
    for app in schema.applications:
        serve.run(app.load(), name=app.name,
                  route_prefix=app.route_prefix, _blocking=False)
        desired[app.name] = app.route_prefix
    for existing in list(serve.status()):
        if existing not in desired:
            serve.delete(existing, _blocking=False)
    return desired
