"""ray_tpu.serve: online model serving (the Ray Serve analog).

Controller reconcile loop + replica actors + power-of-two routing +
stdlib HTTP proxy (SURVEY §2.3 / §3.5).
"""
from ray_tpu.exceptions import AdapterLoadError, ServeOverloadedError
from ray_tpu.serve.api import (HTTPOptions, delete, get_app_handle,
                               get_deployment_handle, get_replica_context,
                               grpc_port, http_port, ingress, list_proxies,
                               proxy_ports, replica_metrics, run, shutdown,
                               start, status)
from ray_tpu.serve.schema import apply_config
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.deployment import Application, Deployment, deployment
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.llm import LLMEngine, LLMServer
from ray_tpu.serve.lora import (delete_adapter, list_adapters,
                                publish_adapter)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.proxy import Request

__all__ = [
    "deployment", "Deployment", "Application", "run", "start", "shutdown",
    "status", "delete", "get_app_handle", "get_deployment_handle",
    "http_port", "grpc_port", "proxy_ports", "list_proxies",
    "replica_metrics",
    "apply_config", "ingress", "batch", "multiplexed",
    "get_multiplexed_model_id", "AutoscalingConfig", "DeploymentConfig",
    "ServeOverloadedError", "AdapterLoadError",
    "publish_adapter", "delete_adapter", "list_adapters",
    "DeploymentHandle", "DeploymentResponse", "Request",
    "LLMEngine", "LLMServer",
]
