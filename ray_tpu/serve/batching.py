"""@serve.batch: transparent request batching inside a replica.

Analog of ray: python/ray/serve/batching.py (@serve.batch,
_BatchQueue).  Calls to the decorated async method are queued; a batch is
launched when `max_batch_size` requests are waiting or
`batch_wait_timeout_s` elapses, whichever first.  The wrapped function
receives a list of requests and must return a list of results of the same
length.

TPU note: XLA compiles one program per shape, so unconstrained dynamic
batch sizes would trigger recompiles.  `pad_batch_to` rounds the batch up
to fixed buckets (e.g. [1, 2, 4, 8]) by repeating the last element —
the bucketed-shapes discipline from SURVEY §7 ("Serve continuous batching
on TPU: static-shape XLA → bucketed shapes").  The extra padded results
are dropped before responding.
"""
from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable


class _BatchQueue:
    def __init__(self, func: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float,
                 pad_batch_to: list[int] | None):
        self.func = func
        self.max_batch_size = max_batch_size
        self.timeout_s = batch_wait_timeout_s
        self.pad_batch_to = sorted(pad_batch_to) if pad_batch_to else None
        self.queue: list[tuple[Any, asyncio.Future]] = []
        self._wakeup: asyncio.Event | None = None
        self._loop_task: asyncio.Task | None = None

    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._wakeup = asyncio.Event()
            self._loop_task = asyncio.get_running_loop().create_task(
                self._batch_loop())

    async def submit(self, item: Any) -> Any:
        self._ensure_loop()
        fut = asyncio.get_running_loop().create_future()
        self.queue.append((item, fut))
        self._wakeup.set()
        return await fut

    async def _batch_loop(self) -> None:
        while True:
            await self._wakeup.wait()
            self._wakeup.clear()
            if not self.queue:
                continue
            # wait for more arrivals up to the batch window
            if len(self.queue) < self.max_batch_size and self.timeout_s > 0:
                try:
                    await asyncio.wait_for(self._full(), self.timeout_s)
                except asyncio.TimeoutError:
                    pass
            batch = self.queue[:self.max_batch_size]
            del self.queue[:len(batch)]
            if self.queue:
                self._wakeup.set()
            await self._run_batch(batch)

    async def _full(self) -> None:
        while len(self.queue) < self.max_batch_size:
            self._wakeup.clear()
            await self._wakeup.wait()

    async def _run_batch(self, batch: list) -> None:
        items = [it for it, _ in batch]
        n = len(items)
        if self.pad_batch_to:
            target = next((b for b in self.pad_batch_to if b >= n),
                          self.pad_batch_to[-1])
            items = items + [items[-1]] * (target - n)
        try:
            results = self.func(items)
            if asyncio.iscoroutine(results):
                results = await results
            results = list(results)
            if len(results) != len(items):
                raise ValueError(
                    f"batched function returned {len(results)} results "
                    f"for a batch of {len(items)}")
            results = results[:n]   # drop only the pad overhang
            for (_, fut), r in zip(batch, results):
                if not fut.done():
                    fut.set_result(r)
        except Exception as e:  # noqa: BLE001
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(func=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01,
          pad_batch_to: list[int] | None = None):
    """Decorator for replica methods: `async def m(self, items: list)`.

    ray: serve/batching.py @serve.batch.
    """
    def wrap(f):
        attr = f"__serve_batch_queue_{f.__name__}"

        if _is_method(f):
            @functools.wraps(f)
            async def method_wrapper(self, item):
                q = getattr(self, attr, None)
                if q is None:
                    q = _BatchQueue(
                        functools.partial(f, self), max_batch_size,
                        batch_wait_timeout_s, pad_batch_to)
                    setattr(self, attr, q)
                return await q.submit(item)
            return method_wrapper

        q = _BatchQueue(f, max_batch_size, batch_wait_timeout_s, pad_batch_to)

        @functools.wraps(f)
        async def func_wrapper(item):
            return await q.submit(item)
        return func_wrapper

    if func is not None:
        return wrap(func)
    return wrap


def _is_method(f: Callable) -> bool:
    import inspect

    params = list(inspect.signature(f).parameters)
    return bool(params) and params[0] == "self"
