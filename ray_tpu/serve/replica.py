"""Replica actor: hosts one copy of a deployment's user callable.

Analog of ray: python/ray/serve/_private/replica.py (ReplicaActor).  Async
actor: requests overlap up to max_ongoing_requests; sync user code runs on a
thread pool so the event loop keeps serving queue-length probes (the same
reason the reference's replica is an asyncio actor).
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import dataclasses
import inspect
import time
from typing import Any


@dataclasses.dataclass
class ReplicaContext:
    """Identity of the replica the calling code runs inside (ray:
    serve.get_replica_context / ReplicaContext)."""
    app_name: str
    deployment: str
    replica_tag: str
    servable_object: Any


# Per-call context (ContextVar: carries into the task handling one
# request and, via copy_context().run, into pool threads) with a
# process-global fallback for __init__-time calls.  The fallback alone
# is wrong when one process hosts several replicas — e.g. every TPU
# deployment's replicas share the host's single device worker — because
# the last-constructed replica would clobber the rest.
import contextvars

_ctx_var: contextvars.ContextVar = contextvars.ContextVar(
    "raytpu_serve_replica_ctx", default=None)
_current_context: ReplicaContext | None = None

_METRICS = None


def _replica_metrics():
    """Per-deployment request series (utils.metrics registry → the
    telemetry timeline + dashboard /metrics): req/s and live queue
    depth for deployments that run no LLM engine (the engine exports
    its own richer serve_llm_* series)."""
    global _METRICS
    if _METRICS is None:
        from ray_tpu.utils import metrics as um

        # (app, deployment, replica): deployment names default to the
        # class name, so two apps' same-named deployments would
        # otherwise merge into one series (the serve_prefix_tier2_bytes
        # precedent) — and the replica tag keeps N replicas' gauges
        # distinct even when they share one process (TPU deployments
        # co-host every replica on the device worker); the reader sums
        # per-replica latest values, never trusts one series key to
        # mean "the deployment".
        tk = ("app", "deployment", "replica")
        _METRICS = {
            "processed": um.get_or_create(
                um.Counter, "serve_replica_processed",
                "Requests completed by this replica", tk),
            "ongoing": um.get_or_create(
                um.Gauge, "serve_replica_ongoing",
                "Requests queued + executing on this replica", tk),
            "rejected": um.get_or_create(
                um.Counter, "serve_replica_rejected",
                "Requests rejected by bounded-queue admission", tk),
        }
    return _METRICS


def get_current_context() -> ReplicaContext | None:
    return _ctx_var.get() or _current_context


class Replica:
    """Created via ActorClass(Replica).options(max_concurrency=...)."""

    def __init__(self, cls, init_args: tuple, init_kwargs: dict,
                 max_ongoing_requests: int, user_config: Any = None,
                 app_name: str = "default", deployment: str = "",
                 max_queued_requests: int = -1):
        self._cls = cls
        self._max_ongoing = max_ongoing_requests
        self._num_ongoing = 0
        self._num_processed = 0
        # Bounded admission queue (overload control): requests waiting
        # past max_ongoing_requests count against this budget; beyond
        # it (per priority tier) the request rejects EARLY with
        # ServeOverloadedError instead of queueing unboundedly.
        # -1 = default bound of 2 x max_ongoing; kill switch
        # RAY_TPU_SERVE_ADMISSION=0 restores unbounded queues.
        self._max_queued = (2 * max_ongoing_requests
                            if max_queued_requests < 0
                            else max_queued_requests)
        self._num_rejected = 0
        # Recent queue-wait samples (ms, slot-acquisition wait) — the
        # non-LLM deployment's SLO signal for the controller's scaling
        # loop (LLM engines report their own richer window via stats).
        # Age-bounded: a spike's tail must not report its p99 forever.
        from ray_tpu.serve import slo

        self._queue_waits = slo.LatencyWindow(maxlen=256)
        # EWMA service seconds — sizes ServeOverloadedError.retry_after_s.
        self._svc_ewma_s = 0.0
        # Replica-side concurrency bound: routers cap dispatch too, but
        # multiple handles can race past their local counts (ray: replica
        # enforces max_ongoing_requests itself).  Bounds async handlers as
        # well — the thread pool only bounds sync ones.
        self._slots = asyncio.Semaphore(max_ongoing_requests)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(2, max_ongoing_requests))
        import ray_tpu

        global _current_context
        ctx = ray_tpu.get_runtime_context()
        self._context = ReplicaContext(
            app_name=app_name, deployment=deployment,
            replica_tag=ctx.get_actor_id() or "", servable_object=None)
        _current_context = self._context
        token = _ctx_var.set(self._context)
        try:
            self._instance = cls(*init_args, **init_kwargs)
        finally:
            _ctx_var.reset(token)
        self._context.servable_object = self._instance
        if user_config is not None:
            self._reconfigure_sync(user_config)

    def _reconfigure_sync(self, user_config: Any) -> None:
        fn = getattr(self._instance, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    async def reconfigure(self, user_config: Any) -> None:
        """Apply a new user_config without restarting (ray: replica.py
        reconfigure path driven by DeploymentState on config-only changes)."""
        fn = getattr(self._instance, "reconfigure", None)
        if fn is None:
            return
        if inspect.iscoroutinefunction(fn):
            await fn(user_config)
        else:
            await asyncio.get_running_loop().run_in_executor(
                self._pool, fn, user_config)

    def _admit_or_reject(self, priority, args: tuple,
                         kwargs: dict) -> None:
        """Bounded-queue admission decision (overload control): a
        request arriving while `max_queued_requests` others already
        wait for a slot rejects EARLY with a typed, retriable
        ServeOverloadedError — bounded queue wait instead of a timeout
        storm.  Priority tiers: HIGH may use 2x the budget, LOW half
        (serve/slo.py queue_budget).  Runs BEFORE _num_ongoing is
        incremented, so a rejected request never pollutes the router /
        autoscaler load signal."""
        from ray_tpu.serve import slo

        if not slo.admission_on():
            return
        budget = slo.queue_budget(
            slo.request_priority(priority, args, kwargs),
            self._max_queued)
        # Reject iff the tier's queue budget is consumed: compare the
        # FULL ongoing count so budget 0 ('no queue') still admits to
        # free execution slots (queued alone can't tell empty from
        # exactly-full).
        if self._num_ongoing < self._max_ongoing + budget:
            return
        queued = max(0, self._num_ongoing - self._max_ongoing)
        self._num_rejected += 1
        try:
            _replica_metrics()["rejected"].inc(1, self._metric_tags())
        except Exception:  # noqa: BLE001 - metrics never block serving
            pass
        from ray_tpu.exceptions import ServeOverloadedError

        # How long until a queue slot plausibly frees: the wave ahead
        # of this request, served max_ongoing-wide at the EWMA service
        # time.
        retry = (queued + 1) * max(self._svc_ewma_s, 0.01) \
            / max(1, self._max_ongoing)
        raise ServeOverloadedError(
            "replica admission queue full",
            deployment=self._context.deployment,
            queue_depth=queued,
            retry_after_s=round(min(30.0, max(0.05, retry)), 3))

    async def handle_request(self, method: str, args: tuple,
                             kwargs: dict,
                             priority: int | None = None) -> Any:
        """Execute one request (ray: replica.py handle_request).
        `_num_ongoing` counts queued + executing — the queue-length signal
        the router and autoscaler consume."""
        from ray_tpu import failpoints

        if failpoints.ACTIVE:
            # Admission-window failpoint: latency/queue-full injection
            # BEFORE the bounded-queue decision and the ongoing count
            # (serve.admit=delay:... backs up the queue; =error:
            # ServeOverloadedError forges a rejection).
            await failpoints.fire_async("serve.admit")
        self._admit_or_reject(priority, args, kwargs)
        self._num_ongoing += 1
        self._observe_load()
        from ray_tpu import tracing

        t_adm = time.time() if tracing.ENABLED else 0.0
        t_q0 = time.perf_counter()
        try:
            async with self._slots:
                self._queue_waits.observe(
                    "queue", (time.perf_counter() - t_q0) * 1000.0)
                # Flight recorder: how long this request waited for a
                # replica slot (max_ongoing_requests backpressure) —
                # the replica-side "admit" stage of the serve timeline.
                # Context: the handler task's adopted trace (async
                # actor), so it lands in the request's own trace.
                # The t_adm guard skips requests that entered before a
                # LIVE recorder flip (t_adm == 0.0 would record an
                # epoch-0 span).
                if tracing.ENABLED and t_adm:
                    tracing.emit("serve.admit", t_adm,
                                 attrs={"deployment":
                                        self._context.deployment})
                # Failpoint window: the request is admitted but the user
                # callable has not run (crash = replica dies mid-request;
                # the handle must requeue to another replica).
                if failpoints.ACTIVE:
                    await failpoints.fire_async("serve.replica_call")
                target = getattr(self._instance, method)
                token = _ctx_var.set(self._context)
                t_svc0 = time.perf_counter()
                try:
                    if inspect.iscoroutinefunction(target):
                        return await target(*args, **kwargs)
                    # copy_context carries the replica identity into the
                    # pool thread (run_in_executor alone does not).
                    call_ctx = contextvars.copy_context()
                    return await asyncio.get_running_loop().run_in_executor(
                        self._pool,
                        lambda: call_ctx.run(target, *args, **kwargs))
                finally:
                    _ctx_var.reset(token)
                    dur = time.perf_counter() - t_svc0
                    self._svc_ewma_s = dur if not self._svc_ewma_s \
                        else 0.8 * self._svc_ewma_s + 0.2 * dur
        finally:
            self._num_ongoing -= 1
            self._num_processed += 1
            self._observe_load(done=True)

    def _metric_tags(self) -> dict:
        return {"app": self._context.app_name,
                "deployment": self._context.deployment,
                "replica": (self._context.replica_tag or "")[:12]}

    def _observe_load(self, done: bool = False) -> None:
        """Mirror the live queue depth (and completions) into the
        per-replica metric series the telemetry timeline samples."""
        try:
            m = _replica_metrics()
            tags = self._metric_tags()
            m["ongoing"].set(float(self._num_ongoing), tags)
            if done:
                m["processed"].inc(1, tags)
        except Exception:  # noqa: BLE001 - metrics never block serving
            pass

    def handle_request_streaming(self, method: str, args: tuple,
                                 kwargs: dict,
                                 priority: int | None = None):
        """Streaming request: a sync generator the caller invokes with
        num_returns="streaming" — items ship to the consumer as the user
        generator produces them (ray: replica ASGI streaming path).  A
        non-generator result streams as a single item."""
        from ray_tpu import failpoints

        if failpoints.ACTIVE:
            failpoints.fire("serve.admit")
        self._admit_or_reject(priority, args, kwargs)
        self._num_ongoing += 1
        self._observe_load()
        token = _ctx_var.set(self._context)
        try:
            target = getattr(self._instance, method)
            result = target(*args, **kwargs)
            if inspect.isgenerator(result):
                yield from result
            else:
                yield result
        finally:
            _ctx_var.reset(token)
            self._num_ongoing -= 1
            self._num_processed += 1
            self._observe_load(done=True)

    async def get_queue_len(self) -> int:
        """Probe for the power-of-two-choices router (ray:
        replica_scheduler/pow_2_scheduler.py queue-length RPC)."""
        return self._num_ongoing

    async def get_metrics(self) -> dict:
        out = {"num_ongoing": self._num_ongoing,
               "num_processed": self._num_processed,
               "max_ongoing": self._max_ongoing,
               "max_queued": self._max_queued,
               "num_rejected": self._num_rejected,
               # Recent slot-wait percentiles (ms) — the queue-wait SLO
               # signal the controller's scaling loop consumes for
               # deployments that report no engine stats.
               "queue_wait_ms": self._queue_waits.snapshot().get(
                   "queue"),
               "ts": time.time()}
        # Surface the user callable's own stats() (e.g. the LLM engine's
        # cache hit/preempt counters) through the serve state API, not
        # only via direct handle calls.
        fn = getattr(self._instance, "stats", None)
        if fn is not None:
            try:
                r = fn()
                if inspect.isawaitable(r):
                    r = await r
                out["user_stats"] = r
            except Exception:  # noqa: BLE001 - stats must not fail probes
                pass
        # Resident @serve.multiplexed models, for the handle's
        # residency routing (serve/multiplex.py; LLM engines report
        # theirs under user_stats["lora"]["resident"] instead).
        try:
            from ray_tpu.serve import multiplex

            mux = multiplex.resident_models(self._instance)
            if mux:
                out["multiplexed"] = mux
        except Exception:  # noqa: BLE001 - metrics must not fail probes
            pass
        return out

    async def check_health(self) -> bool:
        """User class may define check_health; raising marks unhealthy
        (ray: deployment_state.py health-check polling)."""
        fn = getattr(self._instance, "check_health", None)
        if fn is not None:
            r = fn()
            if inspect.isawaitable(r):
                await r
        return True

    async def prepare_for_shutdown(self) -> None:
        """Drain: wait for ongoing requests, then call user __del__-style
        hook (ray: replica graceful shutdown)."""
        while self._num_ongoing > 0:
            await asyncio.sleep(0.02)
        # Drop this replica's tagged series: the hosting process (the
        # co-hosted device worker) outlives replicas, and an autoscaler
        # cycling replicas all day would otherwise grow the registry —
        # and leave a stale nonzero `ongoing` gauge `ray-tpu top` sums
        # as phantom load — without bound.
        try:
            tags = self._metric_tags()
            for m in _replica_metrics().values():
                m.remove(tags)
        except Exception:  # noqa: BLE001 - metrics never block shutdown
            pass
        fn = getattr(self._instance, "shutdown", None)
        if fn is not None:
            r = fn()
            if inspect.isawaitable(r):
                await r
