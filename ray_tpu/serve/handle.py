"""DeploymentHandle: the client-side router to a deployment's replicas.

Analog of ray: python/ray/serve/handle.py (DeploymentHandle.remote:714,786)
with the power-of-two-choices replica scheduler (ray:
_private/replica_scheduler/pow_2_scheduler.py:51) folded in.  Replica
membership comes from the controller and is cached with a TTL; the
scheduler picks 2 random replicas and routes to the one with the lower
locally-tracked in-flight count (the reference probes queue lengths over
RPC; local counts are the zero-RPC equivalent since every request through
this handle is visible to it).

Threading: `remote()` must never block — handles are used from the driver
(plain threads) AND from inside async replica/proxy actors, where blocking
would deadlock the worker IO loop (membership RPC replies arrive on that
same loop).  Membership refresh therefore runs on a per-handle daemon
router thread; when no membership is cached yet, the request is queued to
that thread and the DeploymentResponse is backed by a Future[ObjectRef].
"""
from __future__ import annotations

import concurrent.futures
import queue as queue_mod
import random
import threading
import time
from typing import Any

from ray_tpu.actor import ActorHandle
from ray_tpu.object_ref import ObjectRef

_MEMBERSHIP_TTL_S = 0.5


class _NoCapacity(RuntimeError):
    """No replica can accept the request right now — retried by the router
    thread until the 30s assignment deadline."""


class DeploymentResponse:
    """Future for one request (ray: serve/handle.py DeploymentResponse).

    Awaitable; `.result()` blocks (only call it off the worker IO loop);
    passing it to another handle call chains on the underlying ObjectRef.
    """

    def __init__(self, ref: ObjectRef | None,
                 ref_future: "concurrent.futures.Future | None" = None):
        self._ref = ref
        self._ref_future = ref_future

    def _to_object_ref(self, timeout_s: float | None = 30.0) -> ObjectRef:
        if self._ref is None:
            self._ref = self._ref_future.result(timeout=timeout_s)
        return self._ref

    def result(self, timeout_s: float | None = None) -> Any:
        import ray_tpu

        return ray_tpu.get(self._to_object_ref(), timeout=timeout_s)

    def __await__(self):
        import asyncio

        async def _resolve():
            ref = self._ref
            if ref is None:
                ref = await asyncio.wrap_future(self._ref_future)
                self._ref = ref
            return await ref

        return _resolve().__await__()

    def __reduce__(self):
        return (DeploymentResponse, (self._to_object_ref(),))


class DeploymentResponseGenerator:
    """Streaming response: iterating yields each item the replica's user
    generator produces, as it is produced (ray: serve/handle.py
    DeploymentResponseGenerator via handle.options(stream=True))."""

    def __init__(self, gen_future: "concurrent.futures.Future"):
        self._gen_future = gen_future
        self._gen = None

    def _resolve(self):
        if self._gen is None:
            self._gen = self._gen_future.result(timeout=30.0)
        return self._gen

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        import ray_tpu

        return ray_tpu.get(next(self._resolve()))

    def __aiter__(self):
        return self

    async def __anext__(self) -> Any:
        import asyncio

        import ray_tpu

        loop = asyncio.get_running_loop()
        gen = await loop.run_in_executor(None, self._resolve)
        ref = await gen.__anext__()
        return await loop.run_in_executor(None, ray_tpu.get, ref)


class DeploymentHandle:
    def __init__(self, deployment: str, app: str, controller_id: str,
                 method_name: str = "__call__", stream: bool = False):
        self.deployment_name = deployment
        self.app_name = app
        self._controller_id = controller_id
        self._method = method_name
        self._stream = stream
        self._lock = threading.Lock()
        self._replicas: list[str] = []      # replica actor ids
        self._handles: dict[str, ActorHandle] = {}
        self._inflight: dict[str, int] = {}
        self._max_ongoing = 0               # 0 = no cap known yet
        self._fetched_at = 0.0
        self._router_q: queue_mod.Queue | None = None
        self._router_thread: threading.Thread | None = None

    # -- membership ---------------------------------------------------------
    def _refresh_blocking(self) -> None:
        """Fetch membership from the controller.  Blocks — router thread /
        driver thread only."""
        import ray_tpu

        info = ray_tpu.get(
            ActorHandle(self._controller_id).get_deployment_info.remote(
                self.app_name, self.deployment_name))
        with self._lock:
            self._fetched_at = time.monotonic()
            self._replicas = list(info["replicas"])
            self._max_ongoing = info.get("max_ongoing", 0)
            for rid in self._replicas:
                self._handles.setdefault(rid, ActorHandle(rid))
                self._inflight.setdefault(rid, 0)
            for rid in list(self._handles):
                if rid not in self._replicas:
                    self._handles.pop(rid)
                    self._inflight.pop(rid, None)

    def _ensure_router(self) -> queue_mod.Queue:
        with self._lock:
            if self._router_q is None:
                self._router_q = queue_mod.Queue()
                self._router_thread = threading.Thread(
                    target=self._router_main, daemon=True,
                    name=f"serve-router-{self.deployment_name}")
                self._router_thread.start()
            return self._router_q

    def _router_main(self) -> None:
        """Completes queued submits and keeps membership fresh while
        requests are flowing (ray: Router long-poll updates,
        _private/router.py:320)."""
        while True:
            try:
                item = self._router_q.get(timeout=_MEMBERSHIP_TTL_S)
            except queue_mod.Empty:
                item = None
            with self._lock:
                stale = (time.monotonic() - self._fetched_at) \
                    > _MEMBERSHIP_TTL_S
            if stale:
                try:
                    self._refresh_blocking()
                except Exception:  # noqa: BLE001 - controller restarting
                    pass
            if item is None:
                continue
            fut, submit_fn, args, kwargs, deadline = item
            try:
                fut.set_result(submit_fn(args, kwargs))
            except _NoCapacity as e:
                if time.monotonic() > deadline:
                    fut.set_exception(RuntimeError(str(e)))
                else:
                    time.sleep(0.05)
                    self._router_q.put(item)
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

    # -- routing ------------------------------------------------------------
    def _pick(self) -> tuple[str, ActorHandle]:
        """Power-of-two choices over in-flight counts, skipping replicas at
        their max_ongoing_requests cap — the routing-side backpressure of
        ray: pow_2_scheduler.py:51 (replicas over capacity are not sent
        more work; the request queues in the router instead)."""
        with self._lock:
            reps = self._replicas
            if not reps:
                raise _NoCapacity(
                    f"deployment {self.deployment_name!r} has no running "
                    f"replicas")
            cap = self._max_ongoing
            if cap > 0:
                eligible = [r for r in reps
                            if self._inflight.get(r, 0) < cap]
                if not eligible:
                    raise _NoCapacity(
                        f"all replicas of {self.deployment_name!r} are at "
                        f"max_ongoing_requests={cap}")
            else:
                eligible = reps
            if len(eligible) == 1:
                choice = eligible[0]
            else:
                a, b = random.sample(eligible, 2)
                choice = a if self._inflight.get(a, 0) <= \
                    self._inflight.get(b, 0) else b
            self._inflight[choice] = self._inflight.get(choice, 0) + 1
            handle = self._handles[choice]
        return choice, handle

    def _submit(self, args: tuple, kwargs: dict) -> ObjectRef:
        rid, handle = self._pick()
        try:
            args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                         else a for a in args)
            kwargs = {k: (v._to_object_ref()
                          if isinstance(v, DeploymentResponse) else v)
                      for k, v in kwargs.items()}
        except BaseException:
            self._done(rid)
            raise
        ref = handle.handle_request.remote(self._method, args, kwargs)
        ref.future().add_done_callback(lambda _f: self._done(rid))
        return ref

    def _done(self, rid: str) -> None:
        with self._lock:
            if self._inflight.get(rid, 0) > 0:
                self._inflight[rid] -= 1

    def _submit_streaming(self, args: tuple, kwargs: dict):
        """Route one streaming request: returns a
        StreamingObjectRefGenerator over the replica generator's items."""
        rid, handle = self._pick()
        try:
            args = tuple(a._to_object_ref()
                         if isinstance(a, DeploymentResponse) else a
                         for a in args)
            kwargs = {k: (v._to_object_ref()
                          if isinstance(v, DeploymentResponse) else v)
                      for k, v in kwargs.items()}
            gen = handle.handle_request_streaming.options(
                num_returns="streaming").remote(self._method, args, kwargs)
        except BaseException:
            self._done(rid)
            raise
        gen.task_done_ref().future().add_done_callback(
            lambda _f: self._done(rid))
        return gen

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        chained_pending = any(
            isinstance(a, DeploymentResponse) and a._ref is None
            for a in list(args) + list(kwargs.values()))
        if self._stream:
            fut: concurrent.futures.Future = concurrent.futures.Future()
            with self._lock:
                have = bool(self._replicas)
            if have and not chained_pending:
                try:
                    fut.set_result(self._submit_streaming(args, kwargs))
                    return DeploymentResponseGenerator(fut)
                except _NoCapacity:
                    fut = concurrent.futures.Future()
            # No membership / unresolved chained response / no capacity:
            # the router thread resolves the generator off the caller's
            # thread (which may be a worker IO loop — never block it).
            self._ensure_router().put(
                (fut, self._submit_streaming, args, kwargs,
                 time.monotonic() + 30.0))
            return DeploymentResponseGenerator(fut)
        # An unresolved chained response would require a blocking wait to
        # convert to an ObjectRef — never do that on the caller's thread
        # (it may be a worker IO loop); hand it to the router thread.
        with self._lock:
            have = bool(self._replicas)
            fresh = (time.monotonic() - self._fetched_at) < _MEMBERSHIP_TTL_S
        if have and not chained_pending:
            if not fresh:    # serve stale, refresh in background
                self._ensure_router()
            try:
                return DeploymentResponse(self._submit(args, kwargs))
            except _NoCapacity:
                pass         # queue to the router thread below
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._ensure_router().put(
            (fut, self._submit, args, kwargs, time.monotonic() + 30.0))
        return DeploymentResponse(None, ref_future=fut)

    def options(self, method_name: str | None = None,
                stream: bool | None = None) -> "DeploymentHandle":
        return DeploymentHandle(self.deployment_name, self.app_name,
                                self._controller_id,
                                method_name or self._method,
                                self._stream if stream is None else stream)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def __repr__(self):
        return (f"DeploymentHandle({self.app_name}/{self.deployment_name}"
                f".{self._method})")

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name, self.app_name,
                                   self._controller_id, self._method,
                                   self._stream))
