"""DeploymentHandle: the client-side router to a deployment's replicas.

Analog of ray: python/ray/serve/handle.py (DeploymentHandle.remote:714,786)
with the power-of-two-choices replica scheduler (ray:
_private/replica_scheduler/pow_2_scheduler.py:51) folded in.  Replica
membership comes from the controller and is cached with a TTL; the
scheduler picks 2 random replicas and routes to the one with the lower
locally-tracked in-flight count (the reference probes queue lengths over
RPC; local counts are the zero-RPC equivalent since every request through
this handle is visible to it).

Threading: `remote()` must never block — handles are used from the driver
(plain threads) AND from inside async replica/proxy actors, where blocking
would deadlock the worker IO loop (membership RPC replies arrive on that
same loop).  Membership refresh therefore runs on a per-handle daemon
router thread; when no membership is cached yet, the request is queued to
that thread and the DeploymentResponse is backed by a Future[ObjectRef].
"""
from __future__ import annotations

import concurrent.futures
import logging
import queue as queue_mod
import random
import threading
import time
from typing import Any

from ray_tpu import tracing
from ray_tpu.actor import ActorHandle
from ray_tpu.object_ref import ObjectRef
from ray_tpu.serve import kv_router

logger = logging.getLogger(__name__)

_MEMBERSHIP_TTL_S = 0.5
# Prefix-summary refresh cadence: the router thread re-pulls every
# replica's cached-prefix digest (serve/kv_router.py) through the
# controller at this TTL while requests are flowing.  Staler than
# membership on purpose — a summary is advisory (a miss only costs a
# recomputed prefix), membership is correctness.
_SUMMARY_TTL_S = 1.0
# Dead-replica requeue budget per request: a submit that lands on a
# replica which dies before producing any response is re-routed to
# another running replica at most this many times (ray: serve retries
# ActorDiedError/ActorUnavailableError requests that never started).
_REQUEUE_BUDGET = 3


def _is_replica_death(e: BaseException) -> bool:
    """True for errors that mean the REPLICA PROCESS failed before (or
    while) handling the request — never for user-code exceptions, which
    arrive as TaskError and must surface to the caller, and never for
    ObjectLostError: a lost RESULT object means the request already
    executed to completion (the side effects are applied) and only the
    stored reply was lost with its node — requeueing would re-execute."""
    from ray_tpu.exceptions import (ActorError, ConnectionLost,
                                    WorkerCrashedError)

    return isinstance(e, (ActorError, WorkerCrashedError, ConnectionLost))


def _as_overload(e: BaseException):
    """The typed early-rejection behind a response failure, or None:
    ServeOverloadedError (admission overflow) or AdapterLoadError (a
    multi-LoRA request whose adapter could not be paged in).  Either
    crosses the process boundary wrapped in TaskError like any user
    exception — unwrap it so callers get the TYPED error (fields:
    queue_depth / retry_after_s, model_id / reason) without fishing
    through .cause.  Both mean the request NEVER RAN — never a replica
    death, so they spend no dead-replica requeue budget."""
    from ray_tpu.exceptions import (AdapterLoadError,
                                    ServeOverloadedError, TaskError)

    typed = (ServeOverloadedError, AdapterLoadError)
    if isinstance(e, typed):
        return e
    if isinstance(e, TaskError) and isinstance(
            getattr(e, "cause", None), typed):
        return e.cause
    return None


class _NoCapacity(RuntimeError):
    """No replica can accept the request right now — retried by the router
    thread until the 30s assignment deadline."""


class DeploymentResponse:
    """Future for one request (ray: serve/handle.py DeploymentResponse).

    Awaitable; `.result()` blocks (only call it off the worker IO loop);
    passing it to another handle call chains on the underlying ObjectRef.
    """

    def __init__(self, ref: ObjectRef | None,
                 ref_future: "concurrent.futures.Future | None" = None,
                 requeue=None):
        self._ref = ref
        self._ref_future = ref_future
        # Callable(exc) -> ObjectRef | None: re-route this request to
        # another running replica after the assigned one died before
        # producing a response (None = budget exhausted / no replica).
        self._requeue = requeue

    def _to_object_ref(self, timeout_s: float | None = 30.0) -> ObjectRef:
        if self._ref is None:
            self._ref = self._ref_future.result(timeout=timeout_s)
        return self._ref

    def result(self, timeout_s: float | None = None) -> Any:
        import ray_tpu
        from ray_tpu.exceptions import GetTimeoutError

        # One deadline for the WHOLE call, spanning requeue retries —
        # each retry gets the remaining budget, not a fresh timeout_s.
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        while True:
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError(
                    f"deployment response not ready within {timeout_s}s")
            try:
                ref = self._to_object_ref(
                    remaining if remaining is not None else 30.0)
                # Ref resolution may have blocked (router-queued
                # submit): re-derive the budget or the get below would
                # run on the stale pre-wait value, overshooting the
                # caller's deadline by the whole resolution wait.
                if deadline is not None:
                    remaining = max(0.0, deadline - time.monotonic())
                value = ray_tpu.get(ref, timeout=remaining)
                # The requeue closure pins the request's args/kwargs;
                # once a response has been produced it can never be used
                # again — release the payload with the closure.
                self._requeue = None
                return value
            except concurrent.futures.TimeoutError:
                bound = timeout_s if timeout_s is not None else 30.0
                raise GetTimeoutError(
                    "deployment response not ready: replica submit did "
                    f"not resolve within {bound}s") from None
            except Exception as e:  # noqa: BLE001 - filtered below
                ov = _as_overload(e)
                if ov is not None:
                    self._requeue = None   # rejected = never ran; typed
                    raise ov from None
                if self._requeue is None or not _is_replica_death(e):
                    raise
                if deadline is None:
                    ref = self._requeue(e)
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise
                    # Cap the re-route wait too: it blocks on membership
                    # refresh + the router thread.
                    ref = self._requeue(e, wait_s=remaining)
                if ref is None:
                    self._requeue = None   # budget exhausted — for good
                    raise
                self._ref = ref

    def __await__(self):
        import asyncio

        async def _resolve():
            while True:
                try:
                    # Ref resolution INSIDE the try: a router-submitted
                    # request whose replica died at submit time fails
                    # the ref_future itself, and must requeue exactly
                    # like a post-submit death (the sync result() path
                    # already does — the two must not diverge).
                    ref = self._ref
                    if ref is None:
                        ref = await asyncio.wrap_future(self._ref_future)
                        self._ref = ref
                    value = await ref
                    self._requeue = None   # see result(): drop the payload
                    return value
                except Exception as e:  # noqa: BLE001 - filtered below
                    ov = _as_overload(e)
                    if ov is not None:
                        self._requeue = None
                        raise ov from None
                    if self._requeue is None or not _is_replica_death(e):
                        raise
                    # The requeue refreshes membership over blocking RPC
                    # — never on this (possibly worker-IO) loop.
                    loop = asyncio.get_running_loop()
                    new_ref = await loop.run_in_executor(
                        None, self._requeue, e)
                    if new_ref is None:
                        self._requeue = None
                        raise
                    self._ref = new_ref

        return _resolve().__await__()

    def __reduce__(self):
        return (DeploymentResponse, (self._to_object_ref(),))


class DeploymentResponseGenerator:
    """Streaming response: iterating yields each item the replica's user
    generator produces, as it is produced (ray: serve/handle.py
    DeploymentResponseGenerator via handle.options(stream=True))."""

    def __init__(self, gen_future: "concurrent.futures.Future",
                 requeue=None):
        self._gen_future = gen_future
        self._gen = None
        self._yielded = 0
        # Callable(exc) -> stream generator | None; only consulted while
        # ZERO items have been produced — a partially-consumed stream
        # must fail (replaying it would duplicate delivered items).
        self._requeue = requeue

    def _resolve(self):
        if self._gen is None:
            self._gen = self._gen_future.result(timeout=30.0)
        return self._gen

    def _try_requeue(self, e: BaseException) -> bool:
        if (self._yielded or self._requeue is None
                or not _is_replica_death(e)):
            return False
        gen = self._requeue(e)
        if gen is None:
            self._requeue = None   # budget exhausted — drop the payload
            return False
        self._gen = gen
        return True

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        import ray_tpu

        while True:
            try:
                item = ray_tpu.get(next(self._resolve()))
            except StopIteration:
                raise
            except Exception as e:  # noqa: BLE001 - filtered in helper
                ov = _as_overload(e)
                if ov is not None:
                    self._requeue = None
                    raise ov from None
                if not self._try_requeue(e):
                    raise
                continue
            self._yielded += 1
            # A partially-consumed stream never requeues; the closure
            # pins the request payload — release both together.
            self._requeue = None
            return item

    def __aiter__(self):
        return self

    async def __anext__(self) -> Any:
        import asyncio

        import ray_tpu

        loop = asyncio.get_running_loop()
        while True:
            try:
                gen = await loop.run_in_executor(None, self._resolve)
                ref = await gen.__anext__()
                item = await loop.run_in_executor(None, ray_tpu.get, ref)
            except StopAsyncIteration:
                raise
            except Exception as e:  # noqa: BLE001 - filtered in helper
                ov = _as_overload(e)
                if ov is not None:
                    self._requeue = None
                    raise ov from None
                # Requeue refreshes membership over blocking RPC: keep
                # it off this (possibly worker-IO) loop.
                if not await loop.run_in_executor(
                        None, self._try_requeue, e):
                    raise
                continue
            self._yielded += 1
            self._requeue = None   # see __next__
            return item


class DeploymentHandle:
    def __init__(self, deployment: str, app: str, controller_id: str,
                 method_name: str = "__call__", stream: bool = False,
                 priority: int | None = None):
        self.deployment_name = deployment
        self.app_name = app
        self._controller_id = controller_id
        self._method = method_name
        self._stream = stream
        # Admission-priority tier for requests through this handle
        # (serve/slo.py: 0=high, 1=normal, 2=low); None = let the
        # replica resolve it from the request payload.
        self._priority = priority
        self._lock = threading.Lock()
        self._replicas: list[str] = []      # replica actor ids
        self._handles: dict[str, ActorHandle] = {}
        self._inflight: dict[str, int] = {}
        self._max_ongoing = 0               # 0 = no cap known yet
        self._fetched_at = 0.0
        self._router_q: queue_mod.Queue | None = None
        self._router_thread: threading.Thread | None = None
        # Cache-aware routing state (serve/kv_router.py): per-replica
        # prefix summaries refreshed by the router thread on their own
        # TTL.  Empty until a replica reports one (non-LLM deployments
        # never do — scoring is skipped and this stays pure pow-2;
        # their poll interval backs off 10x, and polling stops
        # entirely once the handle has been idle for a while).
        self._summaries: dict[str, dict] = {}
        self._summaries_at = 0.0
        self._summary_interval = _SUMMARY_TTL_S
        self._last_request_t = 0.0
        # Tier-2 store view ({page: frozenset(hashes)} — the
        # controller's prefix_store_summary), refreshed with the
        # replica summaries: cluster-RESIDENT prefixes score even when
        # no live radix tree holds them.
        self._store_sets: dict[int, frozenset] = {}
        # Multi-LoRA residency view ({rid: {model_id: entry}}), same
        # poll: LLM engines export resident adapters (+ KV salt / LRU
        # age) under stats()["lora"]["resident"], plain
        # @serve.multiplexed replicas export bare model-id lists.
        # kv_router.choose scores residency so a cold adapter loads on
        # ONE least-loaded replica instead of thrashing the pool.
        self._residency: dict[str, dict] = {}
        # Malformed-summary accounting: a replica whose metrics dict is
        # broken must not silently degrade routing to power-of-two —
        # count every drop and warn ONCE per handle (a gossip
        # regression is a bug to surface, not noise to repeat).
        self._summary_drops = 0
        self._summary_warned = False

    # -- membership ---------------------------------------------------------
    def _refresh_blocking(self) -> None:
        """Fetch membership from the controller.  Blocks — router thread /
        driver thread only."""
        import ray_tpu

        info = ray_tpu.get(
            ActorHandle(self._controller_id).get_deployment_info.remote(
                self.app_name, self.deployment_name))
        with self._lock:
            self._fetched_at = time.monotonic()
            self._replicas = list(info["replicas"])
            self._max_ongoing = info.get("max_ongoing", 0)
            for rid in self._replicas:
                self._handles.setdefault(rid, ActorHandle(rid))
                self._inflight.setdefault(rid, 0)
            for rid in list(self._handles):
                if rid not in self._replicas:
                    self._handles.pop(rid)
                    self._inflight.pop(rid, None)
                    self._summaries.pop(rid, None)

    def _refresh_summaries(self) -> None:
        """Pull every replica's prefix-cache summary through the
        controller's replica_metrics verb (the serve state API detail
        path — the summary rides each replica's user_stats).  Blocks —
        router thread only.  Deployments whose replicas report no
        summary (anything that isn't an LLM engine) just leave the dict
        empty and cost one controller RT per TTL while traffic flows."""
        import ray_tpu

        rm = ray_tpu.get(
            ActorHandle(self._controller_id).replica_metrics.remote(
                self.app_name, deployment=self.deployment_name,
                full_ids=True),
            timeout=10.0)
        reps = rm.get(self.app_name, {}).get(self.deployment_name, {})
        summaries = self._compile_replica_summaries(reps)
        residency = self._compile_residency(reps)
        store_sets: dict[int, frozenset] = {}
        if kv_router.prefix_store_on():
            # Tier-2 directory view, same poll (advisory like the
            # replica summaries; an old controller without the verb
            # just leaves it empty).
            try:
                ss = ray_tpu.get(
                    ActorHandle(self._controller_id)
                    .prefix_store_summary.remote(self.app_name),
                    timeout=10.0)
                for page, hs in ((ss or {}).get("pages") or {}).items():
                    store_sets[int(page)] = frozenset(
                        int(h) for h in hs)
            except Exception:  # noqa: BLE001 - controller restarting
                pass
        with self._lock:
            self._summaries = summaries
            self._store_sets = store_sets
            self._residency = residency
            self._summaries_at = time.monotonic()
            self._summary_interval = _SUMMARY_TTL_S \
                if summaries or store_sets or residency \
                else 10 * _SUMMARY_TTL_S

    def _compile_replica_summaries(self, reps: dict) -> dict:
        """Normalize per-replica prefix summaries for scoring.  A
        replica that reports NO summary (any non-LLM deployment) is
        silently skipped — that's the designed shape.  A summary that
        is PRESENT but unusable (malformed metrics dict, wrong types)
        means the gossip path regressed: count it and warn once,
        instead of silently scoring the replica as no-match forever."""
        summaries = {}
        for rid, m in reps.items():
            if not isinstance(m, dict):
                self._note_malformed_summary(rid, m)
                continue
            raw = ((m.get("user_stats") or {}).get("kv") or {}) \
                .get("prefix_summary")
            if raw is None:
                continue       # not an LLM replica — nothing to score
            s = kv_router.compile_summary(raw)
            if s is None:
                self._note_malformed_summary(rid, raw)
                continue
            summaries[rid] = s
        return summaries

    def _compile_residency(self, reps: dict) -> dict:
        """Per-replica resident-adapter view out of the same metrics
        poll: {rid: {model_id: entry}}.  LLM engines report
        stats()["lora"]["resident"] = {mid: {"salt", "version",
        "age"}}; plain @serve.multiplexed replicas report a bare
        model-id list/dict under "multiplexed" (no KV salt — routing
        still scores residency, just without salted prefix depth).
        Replicas reporting neither are simply absent."""
        residency: dict[str, dict] = {}
        for rid, m in reps.items():
            if not isinstance(m, dict):
                continue       # counted by the summary compile already
            ents: dict = {}
            lora = (m.get("user_stats") or {}).get("lora")
            if isinstance(lora, dict) \
                    and isinstance(lora.get("resident"), dict):
                ents.update(lora["resident"])
            mux = m.get("multiplexed")
            if mux is None:
                mux = (m.get("user_stats") or {}).get("multiplexed")
            if isinstance(mux, dict):
                for mid in mux:
                    ents.setdefault(mid, True)
            elif isinstance(mux, (list, tuple, set)):
                for mid in mux:
                    ents.setdefault(mid, True)
            if ents:
                residency[rid] = ents
        return residency

    def _note_malformed_summary(self, rid, raw) -> None:
        self._summary_drops += 1
        if not self._summary_warned:
            self._summary_warned = True
            logger.warning(
                "deployment %r replica %s reported a malformed prefix "
                "summary (%s); scoring it as no-match — cache-aware "
                "routing is silently degrading to power-of-two "
                "(prefix-summary gossip regression?)",
                self.deployment_name, str(rid)[:12],
                type(raw).__name__)

    def _ensure_router(self) -> queue_mod.Queue:
        with self._lock:
            if self._router_q is None:
                self._router_q = queue_mod.Queue()
                self._router_thread = threading.Thread(
                    target=self._router_main, daemon=True,
                    name=f"serve-router-{self.deployment_name}")
                self._router_thread.start()
            return self._router_q

    def _router_main(self) -> None:
        """Completes queued submits and keeps membership fresh while
        requests are flowing (ray: Router long-poll updates,
        _private/router.py:320)."""
        while True:
            try:
                item = self._router_q.get(timeout=_MEMBERSHIP_TTL_S)
            except queue_mod.Empty:
                item = None
            now = time.monotonic()
            with self._lock:
                stale = now - self._fetched_at > _MEMBERSHIP_TTL_S
                # Summary refresh is ADVISORY and must not delay queued
                # submits/requeues (the controller fan-out can block
                # seconds on a dying replica): poll on idle ticks, only
                # while requests have flowed recently, at an interval
                # that backs off 10x for deployments that report no
                # summaries (non-LLM: polling them forever would cost a
                # controller RT per TTL for nothing).  A queue that
                # never drains must not STARVE the poll either — past
                # 5x the interval, refresh anyway (bounded: at most one
                # blocking refresh per 5 TTLs ahead of a queued item).
                age = now - self._summaries_at
                refresh_summaries = (
                    age > self._summary_interval
                    and now - self._last_request_t < 30.0
                    and (item is None
                         or age > 5 * self._summary_interval))
            if stale:
                try:
                    self._refresh_blocking()
                except Exception:  # noqa: BLE001 - controller restarting
                    pass
            if refresh_summaries and kv_router.cache_router_on():
                try:
                    self._refresh_summaries()
                except Exception:  # noqa: BLE001 - controller restarting
                    # Back off on failure too: without advancing the
                    # stamp, a wedged controller would re-block every
                    # idle tick for up to the RPC timeout — exactly the
                    # queued-submit delay this gating exists to avoid.
                    with self._lock:
                        self._summaries_at = time.monotonic()
                        self._summary_interval = 10 * _SUMMARY_TTL_S
            if item is None:
                continue
            fut, submit_fn, args, kwargs, deadline = item
            # PENDING→RUNNING is atomic with a consumer's cancel(): an
            # abandoned submit (requeue caller timed out) is skipped
            # instead of executed-with-no-consumer.  A _NoCapacity
            # retry re-enters here already RUNNING — don't re-claim.
            if not fut.running() and not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(submit_fn(args, kwargs))
            except _NoCapacity as e:
                if time.monotonic() > deadline:
                    # Router-side overload surface: every replica stayed
                    # at its cap (or membership stayed empty) for the
                    # whole assignment window — reject with the typed,
                    # retriable error instead of a bare RuntimeError
                    # (which it still subclasses, for legacy handlers).
                    from ray_tpu.exceptions import ServeOverloadedError

                    with self._lock:
                        depth = sum(self._inflight.values())
                    fut.set_exception(ServeOverloadedError(
                        str(e), deployment=self.deployment_name,
                        queue_depth=depth, retry_after_s=1.0))
                else:
                    time.sleep(0.05)
                    self._router_q.put(item)
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

    # -- routing ------------------------------------------------------------
    def _pick(self, exclude=(), prompt=None, model_id=None,
              explain: dict | None = None) -> tuple[str, ActorHandle]:
        """Power-of-two choices over in-flight counts, skipping replicas at
        their max_ongoing_requests cap — the routing-side backpressure of
        ray: pow_2_scheduler.py:51 (replicas over capacity are not sent
        more work; the request queues in the router instead).  `exclude`
        holds replica ids that already FAILED this request (dead-replica
        requeue must land somewhere else).

        With `prompt` (a token-id list) and cached prefix summaries,
        the replica whose radix cache holds the deepest prefix of the
        prompt wins, discounted by its queue length (kv_router.choose —
        the SGLang cache-aware routing shape).  Capacity still rules:
        a replica at its cap is not a candidate no matter how deep its
        match.  No match anywhere (or RAY_TPU_CACHE_ROUTER=0) → pure
        power-of-two, exactly as before."""
        with self._lock:
            self._last_request_t = time.monotonic()
            reps = [r for r in self._replicas if r not in exclude] \
                if exclude else self._replicas
            if not reps:
                raise _NoCapacity(
                    f"deployment {self.deployment_name!r} has no running "
                    f"replicas"
                    + (f" ({len(exclude)} excluded after failure)"
                       if exclude else ""))
            cap = self._max_ongoing
            if cap > 0:
                eligible = [r for r in reps
                            if self._inflight.get(r, 0) < cap]
                if not eligible:
                    raise _NoCapacity(
                        f"all replicas of {self.deployment_name!r} are at "
                        f"max_ongoing_requests={cap}")
            else:
                eligible = reps
            choice = None
            # Residency routing for multiplexed requests: gated by its
            # own switches (RAY_TPU_LORA + RAY_TPU_LORA_ROUTER — the
            # bench's blind arm turns only the latter off), independent
            # of the base-model cache router.
            lora_pick = (model_id is not None and self._residency
                         and kv_router.lora_on()
                         and kv_router.lora_router_on())
            if lora_pick or (prompt is not None
                             and (self._summaries or self._store_sets)
                             and kv_router.cache_router_on()):
                store = self._store_sets \
                    if self._store_sets and kv_router.prefix_store_on() \
                    else None
                choice = kv_router.choose(
                    prompt, eligible, self._inflight, self._summaries,
                    explain=explain, store=store,
                    model_id=model_id if lora_pick else None,
                    residency=self._residency if lora_pick else None)
            if choice is None:
                if len(eligible) == 1:
                    choice = eligible[0]
                else:
                    a, b = random.sample(eligible, 2)
                    choice = a if self._inflight.get(a, 0) <= \
                        self._inflight.get(b, 0) else b
            self._inflight[choice] = self._inflight.get(choice, 0) + 1
            handle = self._handles[choice]
        return choice, handle

    def _submit(self, args: tuple, kwargs: dict,
                state: dict | None = None) -> ObjectRef:
        # Routing happens OUTSIDE the flight-recorder span: a
        # _NoCapacity attempt (the router thread retries every 50ms for
        # up to 30s) must not burn ring slots on phantom error spans,
        # nor consume the queued_at stamp the eventually-successful
        # attempt needs for its serve.queue span.
        explain: dict = {}
        rid, handle = self._pick(
            state["failed"] if state is not None else (),
            prompt=kv_router.extract_prompt(args, kwargs)
            if (self._summaries or self._store_sets) else None,
            model_id=kv_router.extract_model_id(args, kwargs),
            explain=explain)
        if state is not None:
            state["rid"] = rid
        # Flight-recorder route span: roots the request's trace at the
        # handle edge (or joins the caller's — a replica calling its
        # decode pool continues ONE request trace across processes);
        # the actor_call submitted inside the span parents to it.
        with tracing.span(
                "serve.route",
                ctx=state.get("trace") if state is not None else None,
                attrs={"deployment": self.deployment_name,
                       "replica": rid, **explain}):
            t_q = state.pop("queued_at", None) if state is not None \
                else None
            if t_q is not None:
                # Time the request waited in the router-thread queue
                # (no membership / no capacity) before routing.
                tracing.emit("serve.queue", t_q)
            try:
                args = tuple(a._to_object_ref()
                             if isinstance(a, DeploymentResponse)
                             else a for a in args)
                kwargs = {k: (v._to_object_ref()
                              if isinstance(v, DeploymentResponse) else v)
                          for k, v in kwargs.items()}
            except BaseException:
                self._done(rid)
                raise
            pr = {} if self._priority is None \
                else {"priority": self._priority}
            ref = handle.handle_request.remote(self._method, args,
                                               kwargs, **pr)
            ref.future().add_done_callback(lambda _f: self._done(rid))
            return ref

    def _done(self, rid: str) -> None:
        with self._lock:
            if self._inflight.get(rid, 0) > 0:
                self._inflight[rid] -= 1

    def _submit_streaming(self, args: tuple, kwargs: dict,
                          state: dict | None = None):
        """Route one streaming request: returns a
        StreamingObjectRefGenerator over the replica generator's items."""
        # See _submit: routing stays OUTSIDE the span so _NoCapacity
        # retries neither emit phantom spans nor eat the queue stamp.
        explain: dict = {}
        rid, handle = self._pick(
            state["failed"] if state is not None else (),
            prompt=kv_router.extract_prompt(args, kwargs)
            if (self._summaries or self._store_sets) else None,
            model_id=kv_router.extract_model_id(args, kwargs),
            explain=explain)
        if state is not None:
            state["rid"] = rid
        with tracing.span(
                "serve.route",
                ctx=state.get("trace") if state is not None else None,
                attrs={"deployment": self.deployment_name,
                       "stream": True, "replica": rid, **explain}):
            t_q = state.pop("queued_at", None) if state is not None \
                else None
            if t_q is not None:
                tracing.emit("serve.queue", t_q)
            try:
                args = tuple(a._to_object_ref()
                             if isinstance(a, DeploymentResponse) else a
                             for a in args)
                kwargs = {k: (v._to_object_ref()
                              if isinstance(v, DeploymentResponse) else v)
                          for k, v in kwargs.items()}
                pr = {} if self._priority is None \
                    else {"priority": self._priority}
                gen = handle.handle_request_streaming.options(
                    num_returns="streaming").remote(self._method, args,
                                                    kwargs, **pr)
            except BaseException:
                self._done(rid)
                raise
            gen.task_done_ref().future().add_done_callback(
                lambda _f: self._done(rid))
            return gen

    def _make_requeue(self, submit_fn, args: tuple, kwargs: dict,
                      state: dict):
        """Bounded dead-replica requeue for one request: refresh
        membership (dropping the dead replica), then re-route through
        the router thread — which keeps retrying while the controller
        starts a replacement — to a replica that has not already failed
        this request.  Returns the new ref/generator or None (budget
        spent / nothing to route to: the original error surfaces)."""
        def _requeue(exc: BaseException, wait_s: float = 35.0):
            if state["budget"] <= 0:
                return None
            state["budget"] -= 1
            if state.get("rid"):
                state["failed"].add(state["rid"])
            try:
                self._refresh_blocking()
            except Exception:  # noqa: BLE001 - controller restarting
                pass
            fut: concurrent.futures.Future = concurrent.futures.Future()
            state["queued_at"] = time.time()
            self._ensure_router().put(
                (fut, submit_fn, args, kwargs,
                 time.monotonic() + min(30.0, wait_s)))
            try:
                return fut.result(timeout=wait_s)
            except Exception:  # noqa: BLE001 - surface the ORIGINAL error
                # Still queued (router wedged in a refresh): cancel so
                # the router skips it — executing an abandoned submit
                # would dispatch a request nobody consumes.
                fut.cancel()
                return None
        return _requeue

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        chained_pending = any(
            isinstance(a, DeploymentResponse) and a._ref is None
            for a in list(args) + list(kwargs.values()))
        # Per-request routing state: requeue budget + replicas that
        # already failed it (see _make_requeue) + the caller's trace
        # context, captured HERE (API edge, caller thread) because the
        # submit may execute later on the router thread, which has no
        # ambient context of its own.
        state = {"budget": _REQUEUE_BUDGET, "failed": set(), "rid": None,
                 "trace": tracing.capture() if tracing.ENABLED else None}
        if self._stream:
            def submit_stream(a, k):
                return self._submit_streaming(a, k, state=state)

            requeue = self._make_requeue(submit_stream, args, kwargs,
                                         state)
            fut: concurrent.futures.Future = concurrent.futures.Future()
            with self._lock:
                have = bool(self._replicas)
            if have and not chained_pending:
                try:
                    fut.set_result(submit_stream(args, kwargs))
                    return DeploymentResponseGenerator(fut,
                                                       requeue=requeue)
                except _NoCapacity:
                    fut = concurrent.futures.Future()
            # No membership / unresolved chained response / no capacity:
            # the router thread resolves the generator off the caller's
            # thread (which may be a worker IO loop — never block it).
            state["queued_at"] = time.time()
            self._ensure_router().put(
                (fut, submit_stream, args, kwargs,
                 time.monotonic() + 30.0))
            return DeploymentResponseGenerator(fut, requeue=requeue)

        def submit(a, k):
            return self._submit(a, k, state=state)

        requeue = self._make_requeue(submit, args, kwargs, state)
        # An unresolved chained response would require a blocking wait to
        # convert to an ObjectRef — never do that on the caller's thread
        # (it may be a worker IO loop); hand it to the router thread.
        with self._lock:
            have = bool(self._replicas)
            fresh = (time.monotonic() - self._fetched_at) < _MEMBERSHIP_TTL_S
        if have and not chained_pending:
            if not fresh:    # serve stale, refresh in background
                self._ensure_router()
            try:
                return DeploymentResponse(submit(args, kwargs),
                                          requeue=requeue)
            except _NoCapacity:
                pass         # queue to the router thread below
        fut: concurrent.futures.Future = concurrent.futures.Future()
        state["queued_at"] = time.time()
        self._ensure_router().put(
            (fut, submit, args, kwargs, time.monotonic() + 30.0))
        return DeploymentResponse(None, ref_future=fut, requeue=requeue)

    def options(self, method_name: str | None = None,
                stream: bool | None = None,
                priority: int | None = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name, self._controller_id,
            method_name or self._method,
            self._stream if stream is None else stream,
            self._priority if priority is None else priority)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def __repr__(self):
        return (f"DeploymentHandle({self.app_name}/{self.deployment_name}"
                f".{self._method})")

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name, self.app_name,
                                   self._controller_id, self._method,
                                   self._stream, self._priority))
