"""Prefix-cache-aware replica routing: hashing, summaries, scoring.

The cluster half of the radix prefix cache (serve/kv_blocks.py).  A
single replica's cache only helps requests that happen to land on it;
under power-of-two routing a popular shared prefix ends up recomputed
on every replica it bounces across.  SGLang-style cache-aware routing
(Zheng et al. 2024: the router keeps an approximation of each worker's
radix tree) fixes that: route a request to the replica that already
holds the longest prefix of its prompt, unless that replica's queue
says otherwise.

Three pieces, all host-side and dependency-free so the DeploymentHandle
can import this module without touching jax or the runtime:

  - **Chained block hashes** (`chain_hash` / `prompt_hashes`): block i's
    hash commits to the whole prefix through block i (blake2b over the
    parent hash + the block's token ids), so set-membership of h_i
    alone proves the replica caches blocks 0..i.  blake2b, NOT Python's
    `hash()` — the router and the replicas live in different processes
    and `PYTHONHASHSEED` randomizes `hash()` per process.
  - **Compact summaries**: each BlockManager exports its cached tree as
    the set of node hashes plus an order-independent XOR digest
    (`prefix_summary`); the handle's router thread refreshes these
    through the controller's `replica_metrics` verb on a TTL.
  - **Scoring** (`choose`): matched-prefix depth in blocks, discounted
    by the replica's locally-tracked in-flight count — a deep match on
    a drowning replica loses to an idle one.  No replica matches →
    None, and the caller falls back to pure power-of-two choices.

Kill switch: RAY_TPU_CACHE_ROUTER=0 disables scoring AND the summary
polling (read per call, so one process can A/B it in the same run).
RAY_TPU_PD_DISAGG gates the prefill/decode split (serve/llm.py) and
lives here with its sibling so both cluster-serving switches are in one
place.
"""
from __future__ import annotations

import hashlib
import os

# Root of every hash chain (the empty prefix).
ROOT_HASH = 0

# Queue-length discount: one in-flight request costs a candidate this
# many blocks of matched depth (RAY_TPU_CACHE_ROUTER_ALPHA).
_DEFAULT_ALPHA = 1.0

# Adapter-residency bonus: a replica with the request's LoRA adapter
# already device-resident scores as if it held this many extra blocks
# of matched prefix (RAY_TPU_LORA_ROUTER_BETA) — a resident replica
# must beat a cold one unless its queue is deeply worse, or every
# request cold-thrashes the whole pool's adapter slots.
_DEFAULT_LORA_BETA = 8.0


def env_on(name: str, default: bool = True) -> bool:
    """Shared kill-switch truthiness rule (one copy — serve modules
    import it so RAY_TPU_* switches can never drift apart)."""
    v = os.environ.get(name)
    if v is None:
        return default
    return v not in ("0", "false", "False", "")


def cache_router_on() -> bool:
    """RAY_TPU_CACHE_ROUTER kill switch (checked per call: same-run A/B)."""
    return env_on("RAY_TPU_CACHE_ROUTER")


def pd_disagg_on() -> bool:
    """RAY_TPU_PD_DISAGG kill switch for prefill/decode disaggregation."""
    return env_on("RAY_TPU_PD_DISAGG")


def prefix_store_on() -> bool:
    """RAY_TPU_PREFIX_STORE kill switch for the tiered cluster prefix
    store (serve/prefix_store.py) — lives here with its sibling
    cluster-serving switches so they can never drift apart."""
    return env_on("RAY_TPU_PREFIX_STORE")


def lora_on() -> bool:
    """RAY_TPU_LORA kill switch for multi-LoRA serving (serve/lora.py +
    the engine's adapter path) — read per request/pick: same-run A/B,
    off = every request serves the base model."""
    return env_on("RAY_TPU_LORA")


def lora_router_on() -> bool:
    """RAY_TPU_LORA_ROUTER gates ONLY the router's adapter-residency
    scoring (the bench's blind-routing arm: adapters still serve, but
    placement ignores residency)."""
    return env_on("RAY_TPU_LORA_ROUTER")


def queue_alpha() -> float:
    try:
        return float(os.environ.get("RAY_TPU_CACHE_ROUTER_ALPHA", ""))
    except ValueError:
        return _DEFAULT_ALPHA


def lora_beta() -> float:
    try:
        return float(os.environ.get("RAY_TPU_LORA_ROUTER_BETA", ""))
    except ValueError:
        return _DEFAULT_LORA_BETA


def chain_hash(parent: int, chunk) -> int:
    """Hash of one cached block given its parent's hash: 64-bit blake2b
    over (parent_hash || token ids).  Deterministic across processes —
    the whole routing scheme rides on the router and every replica
    agreeing on these values."""
    h = hashlib.blake2b(digest_size=8)
    h.update(int(parent).to_bytes(8, "little"))
    h.update(b"".join(int(t).to_bytes(8, "little", signed=True)
                      for t in chunk))
    return int.from_bytes(h.digest(), "little")


def prompt_hashes(tokens, page: int, salt: int = 0) -> list[int]:
    """Chained hashes of a prompt's FULL blocks (block granularity —
    the radix tree never caches partial pages, so a trailing partial
    chunk can't match anything).  A non-zero adapter `salt` prefixes
    the FIRST block's hashed bytes — exactly how BlockManager keys
    salted subtrees — so base and per-adapter KV for the same tokens
    hash apart everywhere (tree, store directory, router summaries)."""
    n = len(tokens) // page
    out, h = [], ROOT_HASH
    for i in range(n):
        chunk = tokens[i * page:(i + 1) * page]
        if salt and i == 0:
            chunk = (salt,) + tuple(chunk)
        h = chain_hash(h, chunk)
        out.append(h)
    return out


def summary_digest(hashes) -> int:
    """Order-independent digest of a hash set: XOR folds in O(n) and
    any insertion/eviction flips it — 'did this replica's cache change'
    in one integer."""
    d = 0
    for h in hashes:
        d ^= int(h)
    return d


def compile_summary(summary: dict) -> dict | None:
    """Normalize a replica-reported prefix summary for scoring: the
    hash list becomes a set (membership tests dominate).  Returns None
    for summaries the scorer can't use."""
    if not isinstance(summary, dict):
        return None
    page = summary.get("page")
    hashes = summary.get("hashes")
    if not page or hashes is None:
        return None
    return {"page": int(page), "set": frozenset(int(h) for h in hashes),
            "digest": summary.get("digest", 0)}


def matched_depth(hashes: list[int], cached: frozenset) -> int:
    """Longest prefix (in blocks) of the chained `hashes` present in a
    replica's cached-hash set.  Chaining makes membership of h_i imply
    the full path, so the walk stops at the first miss."""
    depth = 0
    for h in hashes:
        if h not in cached:
            break
        depth += 1
    return depth


def extract_prompt(args: tuple, kwargs: dict):
    """Pull a token-id prompt out of a request payload, if there is
    one: LLM requests through serve carry {"prompt": [ids...], ...}.
    Anything else → None (the deployment isn't prompt-shaped; route by
    queue length alone)."""
    for v in list(args) + list(kwargs.values()):
        if isinstance(v, dict):
            p = v.get("prompt")
            if isinstance(p, (list, tuple)) and p:
                return p
    return None


def extract_model_id(args: tuple, kwargs: dict) -> str | None:
    """Pull a multiplexed model id out of a request payload: LLM
    requests carry {"model_id": "..."} in the request dict, and
    `@serve.multiplexed` handlers take model_id as a kwarg.  Anything
    else → None (base model / not a multiplexed call)."""
    for v in list(args) + list(kwargs.values()):
        if isinstance(v, dict):
            m = v.get("model_id")
            if isinstance(m, str) and m:
                return m
    m = kwargs.get("model_id")
    if isinstance(m, str) and m:
        return m
    return None


def store_depth_tokens(prompt, store: dict) -> int:
    """Deepest CLUSTER-RESIDENT prefix of a prompt, in tokens, over the
    tiered store's hash sets ({page: frozenset(hashes)} — the directory
    summary the handle polls next to the replica summaries).  Stored
    prefixes are reachable from ANY replica (a graft away), so this
    depth is replica-independent."""
    best = 0
    for page, cached in sorted(store.items()):
        d = matched_depth(prompt_hashes(prompt, page), cached) * page
        if d > best:
            best = d
    return best


def _residency_salt(ent) -> int:
    """Adapter salt out of one residency entry.  Replicas export
    {model_id: {"salt": int, "age": s}} (LLM engines) or
    {model_id: True} (plain @serve.multiplexed handlers, no KV salt)."""
    if isinstance(ent, dict):
        try:
            return int(ent.get("salt", 0) or 0)
        except (TypeError, ValueError):
            return 0
    if isinstance(ent, int) and not isinstance(ent, bool):
        return ent
    return 0


def choose(prompt, candidates, inflight: dict, summaries: dict,
           explain: dict | None = None,
           store: dict | None = None,
           model_id: str | None = None,
           residency: dict | None = None) -> str | None:
    """Pick the replica with the best prefix-locality score, or None.

    score(replica) = matched_depth(prompt, replica) - alpha * inflight.
    Every candidate participates (an unmatched idle replica scores 0
    and can beat an overloaded deep match — locality must not create a
    hotspot), but when NO candidate matches at all the answer is None:
    the caller's power-of-two path owns the tie-breaking then.  Ties go
    to the lower in-flight count, then to replica-id order so the
    choice is deterministic under test.

    `store` ({page: frozenset(hashes)}) adds the tier-2 directory's
    view: a stored prefix serves ANY replica (graft on arrival), so
    every candidate's effective depth is at least the store's match —
    a shallow LIVE match can no longer drag the request onto a loaded
    replica when the cluster store holds a deeper one, and the queue
    discount spreads store-served prompts across the pool (each graft
    then makes its target live-warm — the economy compounding).

    `model_id` + `residency` ({rid: {model_id: entry}}) add LoRA
    residency: a candidate with the adapter device-resident gets a
    `lora_beta()` block bonus and its prefix/store match runs under
    the adapter's KV salt (reported in its residency entry — the
    router never derives salts itself); a candidate WITHOUT it matches
    nothing, since its cached base-model prefixes cannot serve the
    adapter.  When the adapter is resident NOWHERE the least-loaded
    candidate wins outright — the cold load lands on one replica
    (which the next poll reports resident: sticky) instead of
    thrashing every pool member.  residency=None disables all of this
    (legacy calls / router kill switch).

    `explain` (optional dict, mutated in place) receives the winner's
    score breakdown — matched depth in blocks, queue discount, score —
    for the flight recorder's router span."""
    alpha = queue_alpha()
    hash_cache: dict[tuple, list[int]] = {}

    def hashes_for(page: int, salt: int = 0) -> list[int]:
        hs = hash_cache.get((page, salt))
        if hs is None:
            hs = prompt_hashes(prompt, page, salt) if prompt else []
            hash_cache[(page, salt)] = hs
        return hs

    def store_match(salt: int = 0) -> tuple[int, int]:
        tok = pg = 0
        if store:
            for page, cached in sorted(store.items()):
                d = matched_depth(hashes_for(page, salt), cached) * page
                if d > tok:
                    tok, pg = d, page
        return tok, pg

    lora = model_id is not None and residency is not None
    if lora and not any(model_id in (residency.get(r) or {})
                        for r in candidates):
        # Cold adapter: deterministic least-loaded placement.
        rid = min(candidates,
                  key=lambda r: (inflight.get(r, 0), r))
        if explain is not None:
            explain.update(lora_cold=True, model_id=model_id,
                           inflight=inflight.get(rid, 0))
        return rid
    store_tok, store_page = store_match()
    best = None            # ((score-key...), rid, depth)
    any_match = False
    for rid in candidates:
        s = summaries.get(rid)
        depth = 0
        page = s["page"] if s is not None else (store_page or 1)
        bonus = 0.0
        res_ent = (residency.get(rid) or {}).get(model_id) \
            if lora else None
        if lora:
            if res_ent is None:
                # Non-resident: cached BASE prefixes can't serve the
                # adapter — no locality at all, queue only.
                eff = 0.0
            else:
                salt = _residency_salt(res_ent)
                if s is not None:
                    depth = matched_depth(hashes_for(s["page"], salt),
                                          s["set"])
                s_tok, _ = store_match(salt)
                eff = max(depth * page, s_tok) / page
                bonus = lora_beta()
        else:
            if s is not None:
                depth = matched_depth(hashes_for(s["page"]), s["set"])
            # Effective depth in the candidate's block units: live
            # match or the (replica-independent) store match,
            # whichever is deeper.
            eff = max(depth * page, store_tok) / page
        if eff > 0 or bonus > 0:
            any_match = True
        q = inflight.get(rid, 0)
        key = (-(eff + bonus - alpha * q), q, rid)
        if best is None or key < best[0]:
            best = (key, rid, depth)
    if not any_match or best is None:
        return None
    if explain is not None:
        explain.update(cache_depth=best[2],
                       cache_score=round(-best[0][0], 3),
                       inflight=best[0][1], alpha=alpha)
        if store_tok:
            explain["store_tokens"] = store_tok
        if lora:
            explain["model_id"] = model_id
    return best[1]
