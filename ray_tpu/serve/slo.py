"""SLO-driven autoscaling and overload-control policy (ISSUE 11).

The decision half of the serve SLO loop, kept pure and host-side so the
controller, the replica, and the LLM server can all import it without
touching jax or the runtime (the `serve/kv_router.py` discipline):

  - **Kill switches** (read per call — same-run A/B):
    ``RAY_TPU_SERVE_AUTOSCALE=0`` freezes replica targets (static
    counts), ``RAY_TPU_SERVE_ADMISSION=0`` restores unbounded replica
    queues (no early rejection, no priority tiers),
    ``RAY_TPU_SERVE_DEGRADE=0`` disables the overload degradation
    ladder (no disagg shedding, no sync-window shrink).
  - **Priority tiers** honored at admission: a HIGH request may use 2x
    the queue budget (reserved headroom), LOW only half — under
    overload the best-effort tier is shed first and the latency-critical
    tier last.
  - **LatencyWindow**: bounded recent-sample store feeding the
    controller's scaling decisions with p50/p90/p99 snapshots — the
    same observations that feed the Prometheus stage histograms, kept
    as raw samples so percentiles are exact over the recent window
    (histogram buckets would quantize the p99 the SLO targets).
  - **OverloadTracker**: hysteresis state machine for the degradation
    ladder (enter a level only after sustained pressure, leave only
    after sustained calm — a one-tick spike must not flap the engine's
    sync window).
  - **slo_desired / pd_rebalance**: the scaling policies themselves,
    pure functions of the metric snapshots so they unit-test without a
    cluster.
"""
from __future__ import annotations

import collections
import threading
import time

from ray_tpu.serve.kv_router import env_on

# Priority tiers (smaller = more important).  A request's tier comes
# from handle.options(priority=...) or a {"priority": n} key in a
# dict-shaped request payload.
PRIORITY_HIGH = 0
PRIORITY_NORMAL = 1
PRIORITY_LOW = 2


def autoscale_on() -> bool:
    """RAY_TPU_SERVE_AUTOSCALE kill switch (controller-side; off =
    static replica counts)."""
    return env_on("RAY_TPU_SERVE_AUTOSCALE")


def admission_on() -> bool:
    """RAY_TPU_SERVE_ADMISSION kill switch (replica-side; off =
    unbounded queues, legacy behavior)."""
    return env_on("RAY_TPU_SERVE_ADMISSION")


def degrade_on() -> bool:
    """RAY_TPU_SERVE_DEGRADE kill switch (replica-side; off = never
    shed disagg / shrink sync windows)."""
    return env_on("RAY_TPU_SERVE_DEGRADE")


def queue_budget(priority: int, max_queued: int) -> int:
    """Per-tier admission queue budget: HIGH gets 2x headroom, LOW
    half (shed first).  A budget of 0 means NO queueing for that tier
    (the request still admits whenever an execution slot is free —
    admission compares ongoing against max_ongoing + budget)."""
    if max_queued <= 0:
        return 0
    if priority <= PRIORITY_HIGH:
        return 2 * max_queued
    if priority >= PRIORITY_LOW:
        return max_queued // 2
    return max_queued


def request_priority(priority, args: tuple = (), kwargs: dict | None
                     = None) -> int:
    """Resolve a request's tier: the handle-level option wins; else a
    {"serve_priority": n} key in a dict payload — a RESERVED key, not
    the app's own "priority" field (an application convention where
    bigger = more urgent would silently invert into the shed-first
    tier); else NORMAL."""
    if priority is not None:
        return int(priority)
    for v in list(args) + list((kwargs or {}).values()):
        if isinstance(v, dict):
            p = v.get("serve_priority")
            if isinstance(p, int) and not isinstance(p, bool):
                return p
    return PRIORITY_NORMAL


def percentiles(samples) -> dict | None:
    """{p50, p90, p99, mean, n} over an iterable of ms samples (None
    when empty).  Nearest-rank on the sorted copy — exact for the
    window sizes used here (<= 512)."""
    vals = sorted(samples)
    if not vals:
        return None
    n = len(vals)

    def pct(q: float) -> float:
        return vals[min(n - 1, int(q * n))]

    return {"p50": round(pct(0.50), 3), "p90": round(pct(0.90), 3),
            "p99": round(pct(0.99), 3),
            "mean": round(sum(vals) / n, 3), "n": n}


class LatencyWindow:
    """Recent latency samples by key ('ttft_ms', 'queue_ms', ...).

    Samples are (monotonic_t, ms) pairs and snapshot() drops anything
    older than `max_age_s`: a spike's tail must AGE OUT, or an idle
    deployment would keep reporting the spike's p99 forever and the
    SLO loop would ratchet it to max_replicas and pin it there.  A
    lock guards observe/snapshot — copying a deque that another thread
    appends to raises 'deque mutated during iteration', and a dropped
    stats() probe would silently blind the router AND the autoscaler
    exactly under load."""

    def __init__(self, maxlen: int = 512, max_age_s: float = 60.0,
                 clock=time.monotonic):
        self._maxlen = maxlen
        self.max_age_s = max_age_s
        self._clock = clock
        self._lock = threading.Lock()
        self._series: dict[str, collections.deque] = {}

    def observe(self, key: str, ms: float) -> None:
        now = self._clock()
        with self._lock:
            d = self._series.get(key)
            if d is None:
                d = self._series.setdefault(
                    key, collections.deque(maxlen=self._maxlen))
            d.append((now, float(ms)))

    def snapshot(self) -> dict:
        cutoff = self._clock() - self.max_age_s
        with self._lock:
            fresh = {key: [ms for t, ms in d if t >= cutoff]
                     for key, d in self._series.items()}
        out = {}
        for key, vals in fresh.items():
            p = percentiles(vals)
            if p is not None:
                out[key] = p
        return out


class OverloadTracker:
    """Hysteresis ladder over a scalar pressure signal (queue depth).

    Levels: 0 = normal, 1 = overloaded (shed disagg to unified),
    2 = severely overloaded (also shrink the decode sync window).
    A level is ENTERED only after the signal holds above its threshold
    for `on_s` continuous seconds; it STEPS DOWN one level after
    `off_s` continuous seconds below that level's own entry threshold
    (so there is no dead band: steady sub-threshold pressure always
    decays the ladder), and `off_s` at-or-below `lo` resets straight
    to 0.  A one-tick spike or dip flaps nothing — every transition
    needs sustain."""

    def __init__(self, hi: float, hi2: float | None = None,
                 lo: float | None = None, on_s: float = 0.25,
                 off_s: float = 1.0, clock=time.monotonic):
        self.hi = hi
        self.hi2 = hi2 if hi2 is not None else 2 * hi
        self.lo = lo if lo is not None else max(0.0, hi / 2)
        self.on_s = on_s
        self.off_s = off_s
        self.level = 0
        self._clock = clock
        self._hi_since: float | None = None
        self._hi2_since: float | None = None
        self._lo_since: float | None = None
        self._below_hi_since: float | None = None
        self._below_hi2_since: float | None = None
        self._last_update: float | None = None

    def _stamp(self, name: str, armed: bool, now: float) -> None:
        # Explicit None checks: a start stamp may legitimately be 0.0
        # (fake clocks under test) — `or` would re-arm it every tick.
        if armed:
            if getattr(self, name) is None:
                setattr(self, name, now)
        else:
            setattr(self, name, None)

    def update(self, depth: float) -> tuple[int, int]:
        """Feed one pressure sample; returns (level, previous_level)."""
        now = self._clock()
        # Updates only arrive with traffic (per request / stats probe).
        # A long gap with LOW depth at its end means the queue drained
        # ~when traffic stopped: credit the gap as sustained calm by
        # backdating the calm stamps, or the FIRST request after a lull
        # would still be served at the spike's degraded level.  Never
        # credit the gap toward the pressure stamps — absence of
        # samples is evidence of calm, not of load.
        gap = None if self._last_update is None \
            else now - self._last_update
        self._last_update = now
        calm_t = now - self.off_s \
            if gap is not None and gap >= self.off_s else now
        self._stamp("_hi_since", depth >= self.hi, now)
        self._stamp("_hi2_since", depth >= self.hi2, now)
        self._stamp("_lo_since", depth <= self.lo, calm_t)
        self._stamp("_below_hi_since", depth < self.hi, calm_t)
        self._stamp("_below_hi2_since", depth < self.hi2, calm_t)

        def held(stamp, dur):
            return stamp is not None and now - stamp >= dur

        prev = self.level
        level = prev
        if held(self._hi2_since, self.on_s):
            level = 2
        elif held(self._hi_since, self.on_s):
            level = max(level, 1)
        # Step-down: sustained below the CURRENT level's entry
        # threshold — without this, steady pressure in (lo, hi) would
        # pin a previously entered level forever (the dead band).
        if level == 2 and held(self._below_hi2_since, self.off_s):
            level = 1
        if level == 1 and held(self._below_hi_since, self.off_s):
            level = 0
        if held(self._lo_since, self.off_s):
            level = 0
        self.level = level
        return level, prev


def slo_desired(cfg, n_running: int, total_ongoing: float,
                p99_ttft_ms: float | None = None,
                p99_queue_ms: float | None = None) -> tuple[int, str]:
    """Desired replica count for one deployment, from load AND SLO
    attainment.  Returns (count, reason) where reason is "load",
    "slo_breach" (an SLO target is violated — step up past the
    load-based answer), or "slo_hold" (near the edge: never downscale
    into a breach).

    The load policy is the legacy ongoing-requests one (cfg.desired);
    the SLO terms only ever RAISE the answer — a deployment with no
    SLO targets behaves exactly as before.  With ZERO ongoing load the
    SLO terms are ignored: a breach with nobody waiting is a stale
    window (the LatencyWindow ages samples out too — belt and
    braces), and acting on it would scale an idle deployment out and
    pin it there."""
    want = cfg.desired(total_ongoing, n_running)
    if total_ongoing <= 0:
        return max(cfg.min_replicas,
                   min(cfg.max_replicas, want)), "load"
    t = getattr(cfg, "target_p99_ttft_ms", None)
    q = getattr(cfg, "target_queue_wait_ms", None)
    breach = ((t is not None and p99_ttft_ms is not None
               and p99_ttft_ms > t)
              or (q is not None and p99_queue_ms is not None
                  and p99_queue_ms > q))
    near = ((t is not None and p99_ttft_ms is not None
             and p99_ttft_ms > 0.8 * t)
            or (q is not None and p99_queue_ms is not None
                and p99_queue_ms > 0.8 * q))
    reason = "load"
    if breach and n_running + 1 > want:
        want, reason = n_running + 1, "slo_breach"
    elif near and want < n_running:
        want, reason = n_running, "slo_hold"
    want = max(cfg.min_replicas, min(cfg.max_replicas, want))
    return want, reason


def pd_rebalance(prefill_snap: dict, decode_snap: dict,
                 prefill_target: int, decode_target: int,
                 prefill_cfg, decode_cfg,
                 ratio: float = 2.0) -> int:
    """Prefill:decode pool-ratio knob (no single-pool autoscaler has
    one): decide whether to shift ONE replica of budget between the
    pools of a disaggregated app, from the prefill-vs-decode stage
    split.  Returns +1 (prefill → decode), -1 (decode → prefill), or 0.

    Signal: each pool's p99 queue-wait (replica admission + engine
    queue) — the stage that grows without bound on the starved side.
    A shift happens only when one side's wait exceeds `ratio`x the
    other's AND the move respects both pools' min/max bounds."""

    def _wait(snap: dict) -> float:
        w = snap.get("p99_queue_ms")
        return float(w) if w is not None else 0.0

    p_wait, d_wait = _wait(prefill_snap), _wait(decode_snap)
    if d_wait > ratio * max(p_wait, 1.0) \
            and decode_target < decode_cfg.max_replicas \
            and prefill_target > prefill_cfg.min_replicas:
        return 1
    if p_wait > ratio * max(d_wait, 1.0) \
            and prefill_target < prefill_cfg.max_replicas \
            and decode_target > decode_cfg.min_replicas:
        return -1
    return 0
